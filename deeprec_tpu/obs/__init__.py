"""Unified telemetry plane — the one subsystem the whole stack reports
into (docs/observability.md).

Three halves:

  * ``obs.metrics`` — a thread-safe ``MetricsRegistry`` of labeled
    counters / gauges / histograms with a fixed-depth ring-buffer time
    series per metric (windowed p99 / rate / slope — the primitives the
    autoscaler and the placement drift detector consume), mergeable
    snapshots, and Prometheus-text exposition (``GET /metrics``).
  * ``obs.trace`` — sampled cross-process request tracing: a trace id
    born at the HTTP edge rides the frontend's TCP frames into the
    backend micro-batcher stages and back, training-side spans come from
    ``PhaseProfiler`` / the checkpoint writer / the tier worker / the
    delta poll loop, and everything serializes to Chrome-trace /
    Perfetto JSON via ``tools/obs_trace.py``.
  * ``obs.schema`` — the single health-payload schema the predictor,
    the socket frontend, and the online loop all emit (the old JSON
    keys stay valid as aliases).

Everything here records only host-side values that already exist — no
device sync, no extra compile (the trace_guard / DRT002 contracts hold
with instrumentation on). ``DEEPREC_OBS=off`` turns the metrics plane
into no-op singletons; tracing is off unless explicitly configured
(``DEEPREC_TRACE=<file>`` or ``trace.configure``).
"""
from deeprec_tpu.obs.metrics import (  # noqa: F401
    MetricsRegistry,
    default_registry,
    metrics_enabled,
    parse_prometheus,
)
from deeprec_tpu.obs import schema, trace  # noqa: F401
