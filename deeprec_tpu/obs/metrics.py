"""Process-wide metrics registry: labeled counters / gauges / histograms
with per-metric ring-buffer time series and Prometheus-text exposition.

Design constraints (why this is not just a dict of floats):

  * **O(1) record, bounded memory.** Histograms reuse the log-bucket
    design of ``training.profiler.LatencyHistogram`` (geometric bucket
    edges, overflow bucket clamped to the tracked exact max) — record is
    a bisect + one lock. This module deliberately does NOT import that
    class: ``obs`` must be importable without jax (the supervisor, the
    trace exporter, and the restart tests run it in bare subprocesses).
  * **A time dimension.** Every metric keeps a fixed-depth ring of
    per-slot aggregates (default 64 slots × 2 s = a ~2 min window), so
    consumers can ask "p99 over the last 60 s", "request rate over the
    window", or "slope of shard imbalance" — the exact primitives the
    multi-host autoscaler and the placement drift detector need, without
    a scrape-and-store stack in the loop.
  * **Mergeable snapshots.** ``snapshot()`` is JSON-ready and
    ``merge_snapshots`` combines them (counters/histograms sum, gauges
    keep the freshest), so the socket frontend can expose one
    ``/metrics`` spanning every backend over the existing STAT-style
    wire protocol — down members re-render their last snapshot
    stale-marked instead of silently disappearing.
  * **Free to turn off.** ``DEEPREC_OBS=off`` makes the registry hand
    out no-op singletons; instrument sites keep their references and pay
    one attribute call. Only host-side values that already exist are
    ever recorded — no device sync, no compile (trace_guard/DRT002 hold
    with instrumentation on).

Label cardinality contract: label values must come from BOUNDED sets
(stage names, table names, member addresses, worker names) — never from
per-request data (user ids, raw keys). Lint rule DRT007
(deeprec_tpu/analysis/lint.py) mechanizes this.
"""
from __future__ import annotations

import bisect
import math
import os
import re
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "MetricsRegistry",
    "default_registry",
    "metrics_enabled",
    "set_metrics_enabled",
    "parse_prometheus",
    "merge_snapshots",
    "render_snapshot",
    "concat_prometheus",
]

# ------------------------------------------------------------ enable switch

_ENABLED: Optional[bool] = None


def metrics_enabled() -> bool:
    """True unless DEEPREC_OBS=off (or 0/false) — the metrics plane is on
    by default because it records only values the process already has."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get("DEEPREC_OBS", "on").lower() not in (
            "off", "0", "false")
    return _ENABLED


def set_metrics_enabled(on: Optional[bool]) -> None:
    """Override the env switch (bench obs-overhead arms, tests).
    ``None`` re-reads DEEPREC_OBS on next use."""
    global _ENABLED
    _ENABLED = on


# ------------------------------------------------------------- ring buffer


class _Ring:
    """Fixed-depth time-sliced aggregate: ``slots`` buckets of ``width``
    seconds each, addressed by epoch so stale slots self-invalidate —
    O(1) per record, no background thread. The caller's lock guards it."""

    __slots__ = ("slots", "width", "epochs", "cells")

    def __init__(self, slots: int, width: float):
        self.slots = slots
        self.width = width
        self.epochs = [-1] * slots
        self.cells: List = [None] * slots

    def cell(self, now: float, make):
        """The live cell for `now`, resetting the slot if its epoch is
        stale. `make()` builds an empty cell."""
        epoch = int(now / self.width)  # noqa: DRT002 — host wall-clock slot math; no device value reaches the obs plane
        i = epoch % self.slots
        if self.epochs[i] != epoch:
            self.epochs[i] = epoch
            self.cells[i] = make()
        return self.cells[i]

    def window(self, now: float, seconds: float) -> List:
        """Cells whose slot overlaps [now - seconds, now], oldest first."""
        lo = int((now - seconds) / self.width)  # noqa: DRT002 — host wall-clock slot math
        hi = int(now / self.width)  # noqa: DRT002 — host wall-clock slot math
        out = []
        for epoch in range(max(lo, hi - self.slots + 1), hi + 1):
            i = epoch % self.slots
            if self.epochs[i] == epoch and self.cells[i] is not None:
                out.append((epoch, self.cells[i]))
        return out


# ----------------------------------------------------------------- metrics


class Counter:
    """Monotonic labeled counter. Ring cells hold the per-slot increment,
    so `window_rate()` answers "events/sec over the last N s" straight
    from process memory."""

    kind = "counter"

    def __init__(self, ring_slots: int, ring_width: float, clock):
        self._lock = threading.Lock()
        self._clock = clock
        self.value = 0.0
        self._ring = _Ring(ring_slots, ring_width)

    def inc(self, n: float = 1.0) -> None:
        now = self._clock()
        with self._lock:
            self.value += n
            cell = self._ring.cell(now, float)
            i = int(now / self._ring.width) % self._ring.slots  # noqa: DRT002 — host wall-clock slot math
            self._ring.cells[i] = cell + n

    def window_delta(self, seconds: float = 60.0) -> float:
        now = self._clock()
        with self._lock:
            return float(sum(c for _, c in self._ring.window(now, seconds)))  # noqa: DRT002 — summing host ring cells (plain floats)

    def window_rate(self, seconds: float = 60.0) -> float:
        return self.window_delta(seconds) / max(seconds, 1e-9)

    def _sample(self):
        with self._lock:
            return {"value": self.value}


class Gauge:
    """Last-write-wins labeled gauge. Ring cells hold (last_t, last_v)
    per slot; `window_slope()` least-squares fits them — the drift
    signal Placement v2's replan cadence keys off."""

    kind = "gauge"

    def __init__(self, ring_slots: int, ring_width: float, clock):
        self._lock = threading.Lock()
        self._clock = clock
        self.value: Optional[float] = None
        self._ring = _Ring(ring_slots, ring_width)

    def _set_locked(self, now: float, v: float) -> None:
        self.value = v
        self._ring.cell(now, lambda: None)
        i = int(now / self._ring.width) % self._ring.slots  # noqa: DRT002 — host wall-clock slot math
        self._ring.cells[i] = (now, v)

    def set(self, v: float) -> None:
        now = self._clock()
        v = float(v)  # noqa: DRT002 — obs gauges take HOST scalars by contract (callers never pass device values)
        with self._lock:
            self._set_locked(now, v)

    def inc(self, n: float = 1.0) -> None:
        # one lock acquisition across read-modify-write: concurrent
        # inc() calls must never lose updates
        now = self._clock()
        with self._lock:
            self._set_locked(now, float((self.value or 0.0) + n))  # noqa: DRT002 — host scalar arithmetic

    def window_points(self, seconds: float = 60.0) -> List[Tuple[float, float]]:
        now = self._clock()
        with self._lock:
            return [c for _, c in self._ring.window(now, seconds)
                    if c is not None]

    def window_slope(self, seconds: float = 60.0) -> Optional[float]:
        """Least-squares d(value)/dt over the window's slot samples
        (None until two slots have data)."""
        pts = self.window_points(seconds)
        if len(pts) < 2:
            return None
        n = len(pts)
        mt = sum(t for t, _ in pts) / n
        mv = sum(v for _, v in pts) / n
        den = sum((t - mt) ** 2 for t, _ in pts)
        if den <= 0:
            return None
        return sum((t - mt) * (v - mv) for t, v in pts) / den

    def _sample(self):
        with self._lock:
            return {"value": self.value}


class Histogram:
    """Log-bucket histogram (the LatencyHistogram design: geometric
    edges from `lo`, overflow clamped to the exact max) plus a ring of
    per-slot bucket counts for windowed percentiles. `summary()` returns
    the same shape as ``training.profiler.LatencyHistogram.summary`` so
    serving's `/v1/stats` keeps its keys with the registry adopted."""

    kind = "histogram"
    GROWTH = 1.5

    def __init__(self, ring_slots: int, ring_width: float, clock,
                 lo: float = 50e-6, hi: float = 120.0):
        bounds = []
        b = lo
        while b < hi:
            bounds.append(b)
            b *= self.GROWTH
        self.bounds = bounds  # upper edge per bucket, in recorded units
        self._nb = len(bounds) + 1  # + overflow
        self._counts = [0] * self._nb
        self._n = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()
        self._clock = clock
        self._ring = _Ring(ring_slots, ring_width)

    # ---- recording

    def record(self, seconds: float) -> None:
        s = float(seconds)  # noqa: DRT002 — obs histograms take HOST durations by contract
        i = bisect.bisect_left(self.bounds, s)
        now = self._clock()
        with self._lock:
            self._counts[i] += 1
            self._n += 1
            self._sum += s
            if s > self._max:
                self._max = s
            cell = self._ring.cell(now, self._empty_cell)
            cell[0][i] += 1
            cell[1][0] += s
            if s > cell[1][1]:
                cell[1][1] = s

    def _empty_cell(self):
        # ([bucket counts], [sum, max])
        return ([0] * self._nb, [0.0, 0.0])

    # ---- totals

    def merge(self, other: "Histogram") -> None:
        with other._lock:
            counts, n = list(other._counts), other._n
            tot, mx = other._sum, other._max
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._n += n
            self._sum += tot
            self._max = max(self._max, mx)

    def _percentile_of(self, counts, n, mx, q: float) -> float:
        if n == 0:
            return 0.0
        target = min(int(q * n), n - 1)  # noqa: DRT002 — host bucket-count arithmetic
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen > target:
                return min(self.bounds[i], mx) if i < len(self.bounds) else mx
        return mx

    def percentile(self, q: float) -> float:
        with self._lock:
            n, counts, mx = self._n, list(self._counts), self._max
        return self._percentile_of(counts, n, mx, q)

    def summary(self) -> Dict[str, float]:
        """{count, mean_ms, p50_ms, p90_ms, p99_ms, max_ms} — the
        LatencyHistogram shape serving's snapshots are built from."""
        with self._lock:
            n, tot, mx = self._n, self._sum, self._max
            counts = list(self._counts)
        pct = lambda q: self._percentile_of(counts, n, mx, q)  # noqa: E731
        return {
            "count": n,
            "mean_ms": round(tot / n * 1e3, 3) if n else 0.0,
            "p50_ms": round(pct(0.50) * 1e3, 3),
            "p90_ms": round(pct(0.90) * 1e3, 3),
            "p99_ms": round(pct(0.99) * 1e3, 3),
            "max_ms": round(mx * 1e3, 3),
        }

    # ---- windowed

    def window_summary(self, seconds: float = 60.0) -> Dict[str, float]:
        """Same summary shape, but over the ring window only — "p99 over
        the last 60 s", the autoscaler's input."""
        now = self._clock()
        counts = [0] * self._nb
        tot = 0.0
        mx = 0.0
        with self._lock:
            for _, (cc, (s, m)) in self._ring.window(now, seconds):
                for i, c in enumerate(cc):
                    counts[i] += c
                tot += s
                mx = max(mx, m)
        n = sum(counts)
        pct = lambda q: self._percentile_of(counts, n, mx, q)  # noqa: E731
        return {
            "count": n,
            "mean_ms": round(tot / n * 1e3, 3) if n else 0.0,
            "p50_ms": round(pct(0.50) * 1e3, 3),
            "p90_ms": round(pct(0.90) * 1e3, 3),
            "p99_ms": round(pct(0.99) * 1e3, 3),
            "max_ms": round(mx * 1e3, 3),
        }

    def _sample(self):
        with self._lock:
            return {
                "bounds": list(self.bounds),
                "counts": list(self._counts),
                "n": self._n,
                "sum": self._sum,
                "max": self._max,
            }


# ------------------------------------------------------------ null metrics


class _NullMetric:
    """Shared no-op stand-in handed out when DEEPREC_OBS=off — every
    recording method is a constant-return bound method, so an
    instrumented hot path pays one attribute call and nothing else."""

    kind = "null"

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def record(self, seconds: float) -> None:
        pass

    def window_delta(self, seconds: float = 60.0) -> float:
        return 0.0

    def window_rate(self, seconds: float = 60.0) -> float:
        return 0.0

    def window_slope(self, seconds: float = 60.0):
        return None

    def window_summary(self, seconds: float = 60.0) -> Dict[str, float]:
        return {"count": 0, "mean_ms": 0.0, "p50_ms": 0.0, "p90_ms": 0.0,
                "p99_ms": 0.0, "max_ms": 0.0}

    summary = window_summary
    value = None


_NULL = _NullMetric()


# -------------------------------------------------------------- registry


_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Thread-safe get-or-create registry of labeled metrics.

    One process-wide instance (``default_registry()``) carries the
    training / online / placement plane; serving components additionally
    create their OWN instance per server so two ModelServers in one
    process never share stage histograms (``/v1/stats`` stays
    per-server), and their ``/metrics`` renders both.
    """

    RING_SLOTS = 64
    RING_WIDTH = 2.0  # seconds per slot → ~128 s of history

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 ring_slots: int = RING_SLOTS,
                 ring_width: float = RING_WIDTH):
        self._lock = threading.Lock()
        self._clock = clock
        self._ring_slots = ring_slots
        self._ring_width = ring_width
        # name -> (kind, help, {label_key: metric})
        self._metrics: Dict[str, Tuple[str, str, Dict]] = {}
        # name -> (help, [(label_key, labels, fn)])
        self._callbacks: Dict[str, Tuple[str, List]] = {}

    # ---- construction

    def _get(self, name: str, kind: str, help: str, labels, make):
        if not metrics_enabled():
            return _NULL
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        key = _label_key(labels)
        with self._lock:
            ent = self._metrics.get(name)
            if ent is None:
                ent = (kind, help, {})
                self._metrics[name] = ent
            if ent[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {ent[0]}, "
                    f"not {kind}")
            m = ent[2].get(key)
            if m is None:
                m = make()
                ent[2][key] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(name, "counter", help, labels, lambda: Counter(
            self._ring_slots, self._ring_width, self._clock))

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(name, "gauge", help, labels, lambda: Gauge(
            self._ring_slots, self._ring_width, self._clock))

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  lo: float = 50e-6, hi: float = 120.0) -> Histogram:
        return self._get(name, "histogram", help, labels, lambda: Histogram(
            self._ring_slots, self._ring_width, self._clock, lo=lo, hi=hi))

    def register_callback(self, name: str, fn: Callable[[], float],
                          help: str = "",
                          labels: Optional[Dict[str, str]] = None) -> None:
        """A gauge evaluated at collection time (queue depths, pool
        sizes) — zero cost between scrapes. Re-registering the same
        (name, labels) replaces the previous callback (a restarted
        server re-binds its queue)."""
        if not metrics_enabled():
            return
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        key = _label_key(labels)
        with self._lock:
            help_, entries = self._callbacks.get(name, (help, []))
            entries = [e for e in entries if e[0] != key]
            entries.append((key, dict(labels or {}), fn))
            self._callbacks[name] = (help_ or help, entries)

    # ---- windowed queries

    def window(self, name: str, labels: Optional[Dict[str, str]] = None,
               seconds: float = 60.0) -> Dict:
        """One windowed answer per metric kind: counters → delta + rate,
        gauges → points + slope, histograms → the summary shape."""
        with self._lock:
            ent = self._metrics.get(name)
            m = ent[2].get(_label_key(labels)) if ent else None
        if m is None:
            return {}
        if m.kind == "counter":
            return {"delta": m.window_delta(seconds),
                    "rate_per_sec": m.window_rate(seconds)}
        if m.kind == "gauge":
            pts = m.window_points(seconds)
            return {"points": len(pts), "last": m.value,
                    "slope_per_sec": m.window_slope(seconds)}
        return m.window_summary(seconds)

    # ---- exposition

    def snapshot(self) -> Dict:
        """JSON-ready view of every series (callbacks evaluated now) —
        the unit the frontend merges across backends over the wire."""
        out: Dict = {"metrics": {}}
        with self._lock:
            items = [(n, k, h, list(series.items()))
                     for n, (k, h, series) in self._metrics.items()]
            cbs = [(n, h, list(entries))
                   for n, (h, entries) in self._callbacks.items()]
        for name, kind, help, series in items:
            out["metrics"][name] = {
                "type": kind, "help": help,
                "series": [{"labels": dict(key), **m._sample()}
                           for key, m in series],
            }
        for name, help, entries in cbs:
            rows = []
            for _, labels, fn in entries:
                try:
                    v = float(fn())  # noqa: DRT002 — collector callbacks return HOST scalars by contract
                except Exception:
                    continue  # a dead callback must not kill the scrape
                rows.append({"labels": labels, "value": v})
            if rows:
                ent = out["metrics"].setdefault(
                    name, {"type": "gauge", "help": help, "series": []})
                ent["series"].extend(rows)
        return out

    def render_prometheus(self,
                          extra_labels: Optional[Dict[str, str]] = None,
                          stale: bool = False) -> str:
        return render_snapshot(self.snapshot(), extra_labels=extra_labels,
                               stale=stale)

    def reset(self) -> None:
        """Drop metric accumulations. Collector callbacks survive: they
        are bindings to live objects (queue depths), not accumulations —
        a stats reset must not unbind them."""
        with self._lock:
            self._metrics.clear()


# -------------------------------------------------- snapshot-level helpers


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_val(v) -> str:
    if v is None or (isinstance(v, float) and math.isnan(v)):
        return "NaN"
    return repr(float(v))


def render_snapshot(snap: Dict,
                    extra_labels: Optional[Dict[str, str]] = None,
                    stale: bool = False) -> str:
    """Prometheus text format from a snapshot() dict. `extra_labels` are
    stamped onto every series (the frontend adds member="host:port");
    `stale=True` additionally stamps stale="1" — how a down backend's
    last-known series stay visible instead of silently disappearing."""
    extra = dict(extra_labels or {})
    if stale:
        extra["stale"] = "1"
    lines: List[str] = []
    for name in sorted(snap.get("metrics", {})):
        ent = snap["metrics"][name]
        kind = ent["type"]
        if ent.get("help"):
            lines.append(f"# HELP {name} {ent['help']}")
        lines.append(f"# TYPE {name} {kind}")
        for s in ent["series"]:
            labels = {**s.get("labels", {}), **extra}
            if kind == "counter":
                lines.append(f"{name}_total{_fmt_labels(labels)} "
                             f"{_fmt_val(s['value'])}")
            elif kind == "gauge":
                lines.append(f"{name}{_fmt_labels(labels)} "
                             f"{_fmt_val(s['value'])}")
            else:  # histogram: cumulative le buckets + sum/count
                cum = 0
                for edge, c in zip(s["bounds"], s["counts"]):
                    cum += c
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels({**labels, 'le': repr(float(edge))})} "
                        f"{cum}")
                cum += s["counts"][len(s["bounds"]):][0] \
                    if len(s["counts"]) > len(s["bounds"]) else 0
                lines.append(
                    f"{name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})} "
                    f"{cum}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_val(s['sum'])}")
                lines.append(f"{name}_count{_fmt_labels(labels)} {s['n']}")
    return "\n".join(lines) + ("\n" if lines else "")


def concat_prometheus(parts: Iterable[str]) -> str:
    """Join independently rendered Prometheus text blocks into ONE valid
    exposition: real Prometheus parsers reject a second `# TYPE` (or
    `# HELP`) line for an already-seen metric family, and the frontend's
    tier `/metrics` renders the same families once per backend member —
    so repeated headers after the first are dropped here."""
    seen: set = set()
    out: List[str] = []
    for part in parts:
        for ln in part.splitlines():
            if ln.startswith("# TYPE ") or ln.startswith("# HELP "):
                key = tuple(ln.split(None, 3)[:3])  # ('#', kind, name)
                if key in seen:
                    continue
                seen.add(key)
            out.append(ln)
    return "\n".join(out) + ("\n" if out else "")


def merge_snapshots(snaps: Iterable[Dict]) -> Dict:
    """Combine snapshots from several processes into one: counters and
    histogram buckets sum, gauges keep the last value seen. Used for the
    tier-total view; the per-member view relabels instead (see
    Frontend.metrics_text)."""
    out: Dict = {"metrics": {}}
    for snap in snaps:
        for name, ent in (snap or {}).get("metrics", {}).items():
            dst = out["metrics"].setdefault(
                name, {"type": ent["type"], "help": ent.get("help", ""),
                       "series": []})
            if dst["type"] != ent["type"]:
                continue  # type clash across processes: keep the first
            by_labels = {_label_key(s.get("labels")): s
                         for s in dst["series"]}
            for s in ent["series"]:
                key = _label_key(s.get("labels"))
                cur = by_labels.get(key)
                if cur is None:
                    by_labels[key] = {**s, "labels": dict(s.get("labels", {}))}
                    dst["series"].append(by_labels[key])
                elif ent["type"] == "counter":
                    cur["value"] = (cur.get("value") or 0.0) + \
                        (s.get("value") or 0.0)
                elif ent["type"] == "gauge":
                    cur["value"] = s.get("value", cur.get("value"))
                else:
                    if cur.get("bounds") == s.get("bounds"):
                        cur["counts"] = [a + b for a, b in
                                         zip(cur["counts"], s["counts"])]
                        cur["n"] = cur["n"] + s["n"]
                        cur["sum"] = cur["sum"] + s["sum"]
                        cur["max"] = max(cur["max"], s["max"])
    return out


_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")


def parse_prometheus(text: str) -> Dict[Tuple[str, str], float]:
    """Strict-enough parser for the text we emit (and the CI gate):
    {(metric_name, label_block): value}. Raises ValueError on a line
    that is neither a comment nor a well-formed sample."""
    out: Dict[Tuple[str, str], float] = {}
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        m = _PROM_LINE.match(ln)
        if not m:
            raise ValueError(f"unparseable metrics line: {ln!r}")
        name, labels, val = m.group(1), m.group(2) or "", m.group(3)
        out[(name, labels)] = float(val) if val != "NaN" else float("nan")
    return out


# --------------------------------------------------------- default registry

_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-wide plane (training loop, supervisor, placement,
    tier workers). Serving servers keep their own instance per server —
    see MetricsRegistry docstring."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT
