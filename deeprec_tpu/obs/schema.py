"""The one health/stats schema every serving surface emits.

Before this module the stack had three near-duplicate health shapes —
``Predictor.health()`` (the `/healthz` body), the socket frontend's
``_health_sweep()`` merge (which invented its own synthetic down-member
entries), and the online ``ServeLoop`` heartbeat stamp (a hand-picked
subset) — plus ad-hoc keys sprinkled per surface. Watchdogs had to know
which shape they were reading.

``health_payload()`` is now the single constructor: every canonical key
is always present (defaulted when unknown), extra surface-specific keys
ride along unchanged, and the payload self-identifies via ``schema``.
The canonical names ARE the historical predictor keys, so every existing
consumer (tests, `/healthz` scrapers, the supervisor's wedge detection)
keeps working unchanged — old keys are the aliases, kept forever.

The same fields are what the metrics plane exposes as gauges
(deeprec_serving_staleness_seconds, ...) — see docs/observability.md for
the catalog.
"""
from __future__ import annotations

from typing import Dict, Optional

HEALTH_SCHEMA = "deeprec.health/1"

# Canonical keys, in emission order. Everything here predates the obs
# plane — consolidation means one constructor, not new spellings.
CANONICAL_HEALTH_KEYS = (
    "status",                     # "ok" | "degraded" | "down" | "error"
    "model_version",
    "step",
    "staleness_seconds",          # age of the last SUCCESSFUL poll round
    "last_update_age_seconds",    # age of the last model change
    "consecutive_poll_failures",
    "last_good_version",
    "quarantined",
)


def health_payload(status: str, *,
                   model_version: Optional[int] = None,
                   step: Optional[int] = None,
                   staleness_seconds: Optional[float] = None,
                   last_update_age_seconds: Optional[float] = None,
                   consecutive_poll_failures: int = 0,
                   last_good_version: Optional[int] = None,
                   quarantined: int = 0,
                   **extra) -> Dict:
    """Build the canonical health dict. `extra` keys (members, reachable,
    member, error, replicas, ...) append after the canonical block so
    every surface stays free to add context without forking the shape."""
    out: Dict = {
        "schema": HEALTH_SCHEMA,
        "status": status,
        "model_version": model_version,
        "step": step,
        "staleness_seconds": staleness_seconds,
        "last_update_age_seconds": last_update_age_seconds,
        "consecutive_poll_failures": consecutive_poll_failures,
        "last_good_version": last_good_version,
        "quarantined": quarantined,
    }
    out.update(extra)
    return out


def is_health_payload(d: Dict) -> bool:
    return isinstance(d, dict) and all(k in d for k in CANONICAL_HEALTH_KEYS)
