"""Sampled cross-process request tracing with Chrome-trace/Perfetto
output.

One trace id is born at the HTTP edge (or extracted from the
``X-Deeprec-Trace`` request header), rides the frontend's length-prefixed
TCP frames into the backend (a flag bit on the PRED frame prefixes the
npz body with two little-endian u64s: trace id, parent span id), and
stamps every micro-batcher stage span (queue / pad / device / post) the
request passes through. Training-side spans — ``PhaseProfiler.phase``,
the checkpoint writer, the multi-tier worker, the delta poll loop —
carry no trace id (they are process-timeline events), but land in the
same files, so ``tools/obs_trace.py`` renders one train→delta→serve
timeline.

Event transport is an append-only JSONL file (one self-contained Chrome
"X" event per line): append mode means a supervisor-restarted worker
keeps extending the same file — the trace survives the process, which is
the point of tracing a fault. ``tools/obs_trace.py`` merges one or many
of these files into ``{"traceEvents": [...]}`` for ui.perfetto.dev.

OFF BY DEFAULT, and free when off: ``span()``/``server_span()`` return a
module-level no-op singleton — no object is allocated on the disabled
path (pinned by a tracemalloc test). Enable with ``DEEPREC_TRACE=<path>``
(sample rate via ``DEEPREC_TRACE_SAMPLE``, default 1.0) or
``trace.configure(path, sample=...)``.
"""
from __future__ import annotations

import atexit
import json
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "configure",
    "shutdown",
    "tracing_enabled",
    "span",
    "server_span",
    "start_request",
    "current",
    "emit",
    "to_header",
    "from_header",
    "pack_wire",
    "unpack_wire",
    "WIRE_BYTES",
]

_lock = threading.Lock()
_path: Optional[str] = None
_sample: float = 1.0
_service: str = ""
_buffer: List[dict] = []
_FLUSH_EVERY = 256
_rng = random.Random()
_tls = threading.local()

# env autoconfiguration: a spawned worker (supervisor, bench subprocess)
# inherits tracing from its parent through the environment
_env_path = os.environ.get("DEEPREC_TRACE")
if _env_path:
    _path = _env_path
    try:
        _sample = float(os.environ.get("DEEPREC_TRACE_SAMPLE", "1.0"))
    except ValueError:
        _sample = 1.0


def tracing_enabled() -> bool:
    return _path is not None


def configure(path: str, sample: float = 1.0, service: str = "") -> None:
    """Start appending spans to `path` (created if missing, appended if
    present — restarts extend, never truncate). `sample` is the fraction
    of edge requests that start a trace; propagated contexts are always
    honored."""
    global _path, _sample, _service
    with _lock:
        _flush_locked()
        _path = path
        _sample = float(sample)  # noqa: DRT002 — host config scalar (name-collision reachability)
        _service = service or ""


def shutdown() -> None:
    """Flush and disable (tests; atexit flushes without disabling)."""
    global _path
    with _lock:
        _flush_locked()
        _path = None


def _flush_locked() -> None:
    global _buffer
    if not _buffer or _path is None:
        _buffer = []
        return
    lines = "".join(json.dumps(e, separators=(",", ":")) + "\n"
                    for e in _buffer)
    _buffer = []
    try:
        with open(_path, "a", encoding="utf-8") as f:
            f.write(lines)
    except OSError:
        pass  # tracing must never take the serving path down


def flush() -> None:
    with _lock:
        _flush_locked()


atexit.register(flush)


# ------------------------------------------------------------ span context


def _new_ctx(parent: Optional[Tuple[int, int]] = None) -> Tuple[int, int]:
    """(trace_id, span_id) — ids are 63-bit so they survive JSON/np
    int64 round trips."""
    tid = parent[0] if parent else _rng.getrandbits(63) or 1
    return (tid, _rng.getrandbits(63) or 1)


def child(ctx: Tuple[int, int]) -> Tuple[int, int]:
    """A fresh span id under `ctx`'s trace (retrospective emitters that
    bypass the span context manager)."""
    return (ctx[0], _rng.getrandbits(63) or 1)


def current() -> Optional[Tuple[int, int]]:
    """The calling thread's active (trace_id, span_id), if a span is
    open on it."""
    return getattr(_tls, "ctx", None)


def emit(name: str, cat: str, t0: float, t1: float,
         ctx: Optional[Tuple[int, int]] = None,
         parent: Optional[int] = None,
         args: Optional[Dict] = None) -> None:
    """Record one complete ("X") event from wall-clock endpoints —
    the retrospective entry point (the micro-batcher accounts stage
    times first and emits after the fact). No-op when tracing is off."""
    if _path is None:
        return
    ev = {
        "name": name,
        "cat": cat or "deeprec",
        "ph": "X",
        "ts": int(t0 * 1e6),  # noqa: DRT002 — host wall-clock microseconds
        "dur": max(int((t1 - t0) * 1e6), 0),  # noqa: DRT002 — host wall-clock microseconds
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0xFFFFFFFF,
    }
    a = dict(args) if args else {}
    if ctx is not None:
        a["trace"] = "%016x" % ctx[0]
        a["span"] = "%016x" % ctx[1]
        if parent is not None:
            a["parent"] = "%016x" % parent
    if _service:
        a.setdefault("service", _service)
    if a:
        ev["args"] = a
    with _lock:
        _buffer.append(ev)
        if len(_buffer) >= _FLUSH_EVERY:
            _flush_locked()


class _Span:
    """An open span: times itself, publishes its ctx as the thread's
    current so nested spans parent under it."""

    __slots__ = ("name", "cat", "ctx", "parent", "_t0", "_prev")

    def __init__(self, name: str, cat: str, ctx: Tuple[int, int],
                 parent: Optional[int]):
        self.name = name
        self.cat = cat
        self.ctx = ctx
        self.parent = parent
        self._t0 = 0.0
        self._prev = None

    def __enter__(self) -> "_Span":
        self._t0 = time.time()
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self

    def __exit__(self, *exc) -> None:
        _tls.ctx = self._prev
        emit(self.name, self.cat, self._t0, time.time(), self.ctx,
             self.parent)


class _NoopSpan:
    """THE disabled-path object: one module-level instance, returned by
    every span() call while tracing is off or the request unsampled —
    the zero-allocation contract tests pin by identity and tracemalloc."""

    __slots__ = ()
    ctx = None
    parent = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()


def span(name: str, cat: str = "",
         ctx: Optional[Tuple[int, int]] = None):
    """A child span of `ctx` (or of the thread's current span). Returns
    the no-op singleton unless tracing is on AND there is a sampled
    context to attach to — bare spans inside an unsampled request cost
    nothing."""
    if _path is None:
        return NOOP_SPAN
    parent = ctx if ctx is not None else getattr(_tls, "ctx", None)
    if parent is None:
        return NOOP_SPAN
    return _Span(name, cat, (parent[0], _rng.getrandbits(63) or 1),
                 parent[1])


def start_request(sample: Optional[float] = None) -> Optional[Tuple[int, int]]:
    """Edge-side sampling decision: a fresh (trace_id, span_id) for this
    request, or None (unsampled / tracing off)."""
    if _path is None:
        return None
    s = _sample if sample is None else sample
    if s < 1.0 and _rng.random() >= s:
        return None
    return _new_ctx()


def server_span(name: str, cat: str = "",
                header: Optional[str] = None,
                ctx: Optional[Tuple[int, int]] = None):
    """The serving entry points' span: continue a propagated context
    (wire prefix or HTTP header), else make the edge sampling decision.
    Returns the no-op singleton when nothing is traced."""
    if _path is None:
        return NOOP_SPAN
    parent = ctx
    if parent is None and header:
        parent = from_header(header)
    if parent is not None:
        return _Span(name, cat, (parent[0], _rng.getrandbits(63) or 1),
                     parent[1])
    fresh = start_request()
    if fresh is None:
        return NOOP_SPAN
    return _Span(name, cat, fresh, None)


def phase_span(name: str, t0: float, t1: float, cat: str = "train") -> None:
    """Training-side timeline event (PhaseProfiler, checkpoint writer,
    tier worker, delta poll): no trace id — rendered on the
    process/thread track. Flushed IMMEDIATELY: these are low-rate
    (save/poll cadence) and the processes emitting them get SIGKILLed by
    design (fault benches) — a buffered span that dies with the process
    defeats the point of tracing the fault."""
    if _path is None:
        return
    emit(name, cat, t0, t1, ctx=getattr(_tls, "ctx", None))
    flush()


# ------------------------------------------------------------ propagation

HEADER = "X-Deeprec-Trace"
WIRE_BYTES = 16  # two little-endian u64s: trace_id, parent span_id


def to_header(ctx: Tuple[int, int]) -> str:
    return "%016x-%016x" % (ctx[0], ctx[1])


def from_header(value: Optional[str]) -> Optional[Tuple[int, int]]:
    if not value:
        return None
    try:
        t, s = value.strip().split("-", 1)
        ctx = (int(t, 16), int(s, 16))
    except ValueError:
        return None
    return ctx if ctx[0] else None


def pack_wire(ctx: Tuple[int, int]) -> bytes:
    import struct

    return struct.pack("<QQ", ctx[0], ctx[1])


def unpack_wire(raw: bytes) -> Optional[Tuple[int, int]]:
    import struct

    if len(raw) < WIRE_BYTES:
        return None
    t, s = struct.unpack("<QQ", raw[:WIRE_BYTES])
    return (t, s) if t else None
