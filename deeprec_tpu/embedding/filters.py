"""Admission filters — vectorized counterparts of DeepRec's filter policies.

Reference: /root/reference/tensorflow/core/framework/embedding/
{filter_policy.h, counter_filter_policy.h, bloom_filter_policy.h}; behavior
spec docs/docs_en/Embedding-Variable.md (Feature Filter section).

The counter filter needs no code here — it gates on the per-slot `freq` array
directly (see table._lookup_resolved). The counting-Bloom filter (CBF) keeps a
compact int sketch so that below-threshold keys never consume a table slot.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from deeprec_tpu.config import CBFFilter
from deeprec_tpu.utils import hashing


def cbf_add(
    cbf: CBFFilter, bloom: jnp.ndarray, uids: jnp.ndarray, counts: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Add `counts` occurrences of each id to the sketch; return the updated
    sketch and the post-update min-estimate per id.

    K hash functions index K cells per key; the estimate is the min over
    cells (conservative, counting-Bloom standard). All K updates are batched
    scatter-adds — no per-key loop.
    """
    M = bloom.shape[0]
    K = cbf.num_hashes()
    cap = jnp.int32((1 << cbf.counter_bits) - 1)
    cells = []
    for k in range(K):
        cells.append(hashing.hash_to_bucket(uids, M, salt=0xB100_0001 + k))
    cell_ix = jnp.stack(cells, axis=0)  # [K, U]
    add = jnp.broadcast_to(counts[None, :], cell_ix.shape)
    bloom = bloom.at[cell_ix.reshape(-1)].add(add.reshape(-1))
    bloom = jnp.minimum(bloom, cap)
    est = jnp.min(bloom[cell_ix], axis=0)  # [U]
    return bloom, est


def cbf_estimate(cbf: CBFFilter, bloom: jnp.ndarray, uids: jnp.ndarray) -> jnp.ndarray:
    """Read-only min-estimate of each id's count."""
    M = bloom.shape[0]
    K = cbf.num_hashes()
    cell_ix = jnp.stack(
        [hashing.hash_to_bucket(uids, M, salt=0xB100_0001 + k) for k in range(K)],
        axis=0,
    )
    return jnp.min(bloom[cell_ix], axis=0)
