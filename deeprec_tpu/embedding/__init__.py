from deeprec_tpu.embedding.table import EmbeddingTable, TableState, UniqueLookup
from deeprec_tpu.embedding.combiners import combine
