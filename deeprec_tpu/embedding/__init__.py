from deeprec_tpu.embedding.table import EmbeddingTable, TableState, UniqueLookup
from deeprec_tpu.embedding.combiners import combine
from deeprec_tpu.embedding.compose import (
    AdaptiveEmbedding,
    DynamicDimEmbedding,
    MultiHashConfig,
    MultiHashTable,
)
from deeprec_tpu.embedding.multi_tier import MultiTierTable, TierStats
