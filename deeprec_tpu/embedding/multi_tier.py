"""Multi-tier embedding storage: HBM working set + host-DRAM overflow.

DeepRec's HbmDramStorage (core/framework/embedding/hbm_dram_storage.h, cache
+ EvictionManager in cache.h/eviction_manager.h) keeps hot keys on the GPU
and migrates cold ones to DRAM with background threads. The TPU translation:
the device table IS the hot tier (fixed-capacity HBM arrays); a host-side
choreography step — run every `sync_every` steps, off the jitted hot path —
demotes cold rows (lowest-frequency LFU or oldest-version LRU) to the native
HostKV store and promotes host-resident rows whose keys reappeared on device.

Promotion correctness: when a demoted key is looked up again, the device
table creates a fresh slot with initializer values. sync() detects device
rows whose key exists in the host tier and whose device freq is LOWER than
the host freq — i.e. freshly re-created — and restores the host row.
Host rows carry the VALUES **and the per-row optimizer slots** (packed
side by side into one wide row), matching DeepRec's DRAM tier storing
full ValuePtrs (hbm_dram_storage.h) — a demoted-then-promoted key resumes
Adagrad/Adam state instead of restarting it. freq/version merge so
admission state survives the round-trip. Per-table scalar slots (e.g.
AdamAsync beta powers) are not per-row state and stay on device.
"""
from __future__ import annotations

import dataclasses
import functools as _ft
import os
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeprec_tpu.analysis.annotations import not_thread_safe
from deeprec_tpu.config import StorageType
from deeprec_tpu.embedding.table import (
    META_DIRTY,
    META_FREQ,
    META_VERSION,
    EmbeddingTable,
    TableState,
    empty_key,
)
from deeprec_tpu.native import HostKV


@not_thread_safe
class DiskKV:
    """Log-structured on-disk row store — the SSD tier
    (dram_ssd_storage.h / ssd_hash_kv.h analog). Rows append to a flat
    record log (key, freq, version, value[dim]); an in-memory index maps
    key -> record offset, so updates are append+repoint and reads are one
    seek per key. `save()` persists the index sidecar; `load()` restores
    it (or rebuilds by scanning the log)."""

    MAGIC = 0xD15C_0001  # log header: magic u32 | dim u32

    def __init__(self, path: str, dim: Optional[int] = None):
        """dim=None reopens an existing log using its header's row width
        (the serving flow, where the packed width — values + optimizer
        slot columns — is only known to the process that wrote it)."""
        import json as _json

        self.path = path
        exists = os.path.exists(path) and os.path.getsize(path) >= 8
        if exists:
            with open(path, "rb") as f:
                magic, hdim = np.frombuffer(f.read(8), "<u4")
            if int(magic) != self.MAGIC:
                raise ValueError(
                    f"{path}: not a DiskKV log (bad magic {magic:#x})"
                )
            if dim is not None and int(hdim) != dim:
                raise ValueError(
                    f"{path}: log rows are {int(hdim)} wide but this table/"
                    f"optimizer layout needs {dim} — the log was written "
                    "under a different configuration"
                )
            dim = int(hdim)
        elif dim is None:
            raise FileNotFoundError(
                f"{path}: dim=None requires an existing log to read the "
                "width from"
            )
        self.dim = dim
        self.rec_bytes = 8 + 4 + 4 + 4 * dim
        self.index: dict = {}
        self.last_reads = 0  # coalesced read count of the last get()
        self._dtype = np.dtype(
            [("key", "<i8"), ("freq", "<i4"), ("ver", "<i4"),
             ("val", "<f4", (dim,))]
        )
        assert self._dtype.itemsize == self.rec_bytes
        self._f = open(path, "r+b" if exists else "w+b")
        if not exists:
            np.asarray([self.MAGIC, dim], "<u4").tofile(self._f)
            self._f.flush()
        log_len = self._f.seek(0, 2)
        if log_len > 8 and os.path.exists(path + ".idx"):
            with open(path + ".idx") as f:
                saved = _json.load(f)
            self.index = {
                int(k): int(v) for k, v in saved.get("index", {}).items()
            }
            # A crash can leave records appended after the last save():
            # scan the tail past the sidecar's recorded length so those
            # keys (and updates) are not silently stale/lost.
            tail_from = int(saved.get("_len", 8))
            if log_len > tail_from:
                self._scan_index(tail_from)
        elif log_len > 8:
            self._scan_index(8)

    def _scan_index(self, from_offset: int):
        """(Re)build index entries from log records at/after from_offset
        (later records win, log order)."""
        end = self._f.seek(0, 2)
        start = 8 + ((max(from_offset, 8) - 8) // self.rec_bytes) * self.rec_bytes
        n = (end - start) // self.rec_bytes
        self._f.seek(start)
        recs = np.fromfile(self._f, self._dtype, n)
        for i, k in enumerate(recs["key"]):
            self.index[int(k)] = start + i * self.rec_bytes

    def __len__(self):
        return len(self.index)

    def _log_records(self) -> int:
        return (self._f.seek(0, 2) - 8) // self.rec_bytes

    def compact(self, min_records: int = 1024, garbage_factor: float = 2.0,
                force: bool = False) -> bool:
        """Rewrite live records into a fresh log when dead records (updates
        and erases the log still carries) dominate: without this, a
        long-running HBM_DRAM_SSD job appends forever and crash-rebuild
        cost grows with the GARBAGE, not the data (the reference's SSD
        tier compacts its record files the same way —
        ssd_hash_kv.h / ssd_record_descriptor.h). Returns True if a
        rewrite happened."""
        total = self._log_records()
        live = len(self.index)
        if not force and (
            total < min_records or total <= garbage_factor * max(live, 1)
        ):
            return False
        tmp = self.path + ".compact"
        offs = sorted(self.index.items(), key=lambda kv: kv[1])
        with open(tmp, "wb") as out:
            np.asarray([self.MAGIC, self.dim], "<u4").tofile(out)
            new_index = {}
            for k, off in offs:
                self._f.seek(off)
                rec = np.fromfile(self._f, self._dtype, 1)
                new_index[k] = out.tell()
                rec.tofile(out)
        # A saved sidecar holds the OLD log's offsets. Remove it BEFORE the
        # log swap: a crash between the swap and a fresh save() must find
        # no sidecar (reopen falls back to a full scan of the new log),
        # never stale offsets into the compacted file.
        had_sidecar = os.path.exists(self.path + ".idx")
        if had_sidecar:
            os.remove(self.path + ".idx")
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "r+b")
        self.index = new_index
        if had_sidecar:
            self.save()
        return True

    def put(self, keys, values, freqs=None, versions=None) -> None:
        n = len(keys)
        recs = np.zeros(n, self._dtype)
        recs["key"] = np.asarray(keys, np.int64)
        recs["freq"] = 0 if freqs is None else np.asarray(freqs, np.int32)
        recs["ver"] = 0 if versions is None else np.asarray(versions, np.int32)
        recs["val"] = np.asarray(values, np.float32).reshape(n, self.dim)
        self._f.seek(0, 2)
        base = self._f.tell()
        recs.tofile(self._f)
        self._f.flush()
        for i, k in enumerate(recs["key"]):
            self.index[int(k)] = base + i * self.rec_bytes
        self.compact()

    def get(self, keys):
        keys = np.asarray(keys, np.int64)
        n = len(keys)
        vals = np.zeros((n, self.dim), np.float32)
        freqs = np.zeros(n, np.int32)
        vers = np.zeros(n, np.int32)
        found = np.zeros(n, bool)
        if not self.index or n == 0:
            return vals, freqs, vers, found
        # C-speed membership prefilter: sync() probes nearly every device
        # key here, while the disk tier usually holds few rows — only seek
        # for actual hits.
        idx_keys = np.fromiter(self.index.keys(), np.int64, len(self.index))
        hit_ix = np.nonzero(np.isin(keys, idx_keys))[0]
        if len(hit_ix) == 0:
            return vals, freqs, vers, found
        # Batched reads: sort hits by log offset and coalesce runs of
        # ADJACENT records into one sequential read — a restore-after-crash
        # promote burst against a freshly compacted log (live records
        # contiguous) collapses to a single read instead of a Python
        # seek+fromfile per row (the reference's SSD tier batches the same
        # way — ssd_hash_kv.h). `last_reads` is the run count, for tests
        # and tier diagnostics.
        offs = np.fromiter(
            (self.index[int(keys[i])] for i in hit_ix), np.int64,
            len(hit_ix),
        )
        order = np.argsort(offs, kind="stable")
        sorted_offs = offs[order]
        starts = np.nonzero(np.diff(sorted_offs) != self.rec_bytes)[0] + 1
        bounds = np.concatenate([[0], starts, [len(sorted_offs)]])
        self.last_reads = len(bounds) - 1
        for a, b in zip(bounds[:-1], bounds[1:]):
            self._f.seek(int(sorted_offs[a]))
            recs = np.fromfile(self._f, self._dtype, int(b - a))
            ii = hit_ix[order[a:b]]
            vals[ii] = recs["val"]
            freqs[ii] = recs["freq"]
            vers[ii] = recs["ver"]
            found[ii] = True
        return vals, freqs, vers, found

    def erase(self, keys) -> None:
        for k in np.asarray(keys, np.int64):
            self.index.pop(int(k), None)

    def save(self) -> None:
        import json as _json

        self._f.flush()
        log_len = self._f.seek(0, 2)
        with open(self.path + ".idx", "w") as f:
            _json.dump({"_len": log_len, "index": self.index}, f)

    def close(self) -> None:
        self.save()
        self._f.close()


def _spill_dim(path: str) -> int:
    """Row width recorded in a spill file's header (native hkv format:
    magic u64, dim u64, n u64; npz fallback: the values array)."""
    if os.path.exists(path):
        with open(path, "rb") as f:
            head = f.read(16)
        if len(head) == 16:
            magic, dim = np.frombuffer(head, "<u8")
            if magic == 0xDEE99EC0011:
                return int(dim)
    npz = path if path.endswith(".npz") else path + ".npz"
    if os.path.exists(npz):
        return int(np.load(npz)["values"].shape[1])
    raise FileNotFoundError(path)


@dataclasses.dataclass
class TierStats:
    demoted: int = 0
    promoted: int = 0
    host_size: int = 0
    device_size: int = 0
    spilled: int = 0  # host -> disk this sync
    disk_size: int = 0


# ------------------------------------------- device-side extraction (async)


@_ft.partial(jax.jit, static_argnums=(0, 1))
def _demote_extract_jit(table, size: int, state: TableState, n_out):
    """Device half of a demotion: pick the `n_out` coldest (LFU) / oldest
    (LRU) occupied rows and GATHER their packed (values + per-row slot)
    rows on device at static budget `size` (ops/compact.quantize_rows
    bucket). Only `size` packed rows cross device->host — the legacy
    sync() pulled the full [C, D] values and every slot array to the host
    just to index a few rows out of them. All outputs are fresh buffers
    (donation-safe for the background IO thread). `keep` is the rebuild
    mask dropping exactly the first `n_out` selected rows."""
    from deeprec_tpu.ops.packed import gather_rows_any
    from deeprec_tpu.optim.sparse import SCALAR_PREFIX

    cfg = table.cfg
    C = state.capacity
    sent = jnp.asarray(empty_key(cfg), state.keys.dtype)
    occ = state.keys != sent
    score = (
        state.version if cfg.ev.storage.cache_strategy == "lru"
        else state.freq
    )
    # unoccupied slots sort last; ties inside a score are argsort-order
    masked = jnp.where(occ, score, jnp.iinfo(jnp.int32).max)
    take = jnp.argsort(masked)[:size].astype(jnp.int32)
    valid = jnp.arange(size, dtype=jnp.int32) < n_out
    cols = [gather_rows_any(state.values, take, C).astype(jnp.float32)]
    for name in sorted(state.slots):
        if name.startswith(SCALAR_PREFIX):
            continue  # per-table scalars stay on device (not per-row state)
        g = gather_rows_any(state.slots[name], take, C)
        cols.append(g.reshape(size, -1).astype(jnp.float32))
    keep = jnp.ones((C,), bool).at[
        jnp.where(valid, take, C)
    ].set(False, mode="drop")
    return {
        "keys": jnp.where(valid, state.keys[take], sent),
        "rows": jnp.concatenate(cols, axis=1),
        "freqs": state.meta[META_FREQ, take],
        "versions": state.version[take],
        "keep": keep,
    }


@jax.jit
def _tier_snapshot_jit(state: TableState):
    """Fresh-buffer copies of (keys, freq, version) for the background
    promote scan — the live leaves may be donated by the next train
    dispatch while the worker is still reading. `version` (last-touched
    step per row) drives the promote-scan diet: only rows touched since
    the previous round can have re-entered the device while a tier copy
    exists."""
    return jnp.copy(state.keys), jnp.copy(state.freq), jnp.copy(state.version)


@_ft.partial(jax.jit, static_argnums=(0,))
def _fold_chunk_jit(table, state: TableState, keys_p, rows_p, freqs_p,
                    vers_p):
    """Compiled fold half of the paging engine: resolve one fixed-size
    chunk of prefetched packed tier rows against the CURRENT device table
    — inserting keys not yet resident (the whole point of paging: the
    row lands BEFORE the lookup that would have fresh-initialized it) —
    and scatter the survivors' values + per-row optimizer slots + meta in
    one program.

    Revalidation is the PR 4 ambiguous-key rule applied at fold time: an
    already-resident key folds only while the current device freq has not
    passed the tier copy's freq (`freq_now <= host_freq` — a freshly
    re-created row). A row that trained past its tier copy while the
    gather was in flight is never clobbered. Freshly INSERTED keys always
    fold (nothing trained there) and take the tier copy's freq/version
    outright — the import_rows restore semantics — plus the dirty bit, so
    an incremental checkpoint between the fold and the key's first lookup
    still saves the row. A key that fails to insert (probe-chain
    exhaustion) is skipped whole: its tier copy stays for the next scan.

    The chunk size is part of the trace signature, so a FIXED chunk
    compiles exactly once per table — the `import_rows(chunk=)` compile
    discipline: 0 steady-state compiles no matter how candidate counts
    vary (short chunks arrive sentinel-padded; sentinel entries never
    insert)."""
    from deeprec_tpu.embedding.table import probe_jit
    from deeprec_tpu.ops.packed import scatter_rows_any
    from deeprec_tpu.optim.sparse import SCALAR_PREFIX

    cfg = table.cfg
    C = state.capacity
    sent = jnp.asarray(empty_key(cfg), state.keys.dtype)
    real = keys_p != sent
    new_keys, slot_ix, created, _failed = probe_jit(
        table, state.keys, keys_p, real
    )
    state = state.replace(keys=new_keys)
    present = (slot_ix >= 0) & real
    created = created & present
    freq_now = jnp.where(
        created, 0, state.freq[jnp.clip(slot_ix, 0)]
    )
    refreshed = present & (freq_now <= freqs_p)
    ix = jnp.where(refreshed, slot_ix, -1).astype(jnp.int32)  # -1 = skip
    D = cfg.dim
    state = state.replace(
        values=scatter_rows_any(state.values, ix, rows_p[:, :D], C)
    )
    off = D
    slots = dict(state.slots)
    for name in sorted(slots):
        if name.startswith(SCALAR_PREFIX):
            continue  # per-table scalars are not per-row state
        w = int(np.prod(slots[name].shape)) // C
        slots[name] = scatter_rows_any(slots[name], ix, rows_p[:, off:off + w], C)
        off += w
    state = state.replace(slots=slots)
    # meta: re-created rows MERGE freq (device touches since re-creation
    # stay counted); inserted rows take the tier copy's freq/version and
    # raise the dirty bit (nothing on device knew them before)
    meta = state.meta
    add_ix = jnp.where(refreshed & ~created, slot_ix, C)
    meta = meta.at[META_FREQ, add_ix].add(
        freqs_p.astype(jnp.int32), mode="drop"
    )
    new_ix = jnp.where(refreshed & created, slot_ix, C)
    meta = meta.at[META_FREQ, new_ix].set(
        freqs_p.astype(jnp.int32), mode="drop"
    )
    meta = meta.at[META_VERSION, new_ix].set(
        vers_p.astype(jnp.int32), mode="drop"
    )
    meta = meta.at[META_DIRTY, new_ix].set(1, mode="drop")
    state = state.replace(meta=meta)
    return state, refreshed, present


class MultiTierTable:
    """Wraps an EmbeddingTable with a host overflow tier.

    Usage: call `sync(state, step)` periodically from the host loop (e.g.
    every N steps or at checkpoint time). Lookup/apply stay the plain
    compiled table ops — the tier logic never touches the hot path, which is
    what makes this design TPU-viable.
    """

    def __init__(
        self,
        table: EmbeddingTable,
        high_watermark: float = 0.8,
        low_watermark: float = 0.6,
        storage_path: Optional[str] = None,
        slot_fills: Optional[tuple] = None,
        scan_diet: bool = True,
        row_cache_bytes: int = 0,
    ):
        cfg = table.cfg
        self.table = table
        self.high = high_watermark
        self.low = low_watermark
        self.cache_strategy = cfg.ev.storage.cache_strategy
        self.storage_path = storage_path or cfg.ev.storage.storage_path
        self.host_capacity = cfg.ev.storage.host_capacity
        # Host/disk tiers are created lazily at the first sync(): their row
        # width is D + the widths of the per-row optimizer slots, which
        # only the live TableState knows. Packing slots into the tier row
        # (DeepRec's DRAM tier stores full ValuePtrs, hbm_dram_storage.h)
        # is what lets a demote/promote round-trip preserve optimizer
        # state.
        self.host: Optional[HostKV] = None
        self.disk: Optional[DiskKV] = None
        self._slot_layout: Optional[tuple] = None  # ((name, width), ...)
        # Optimizer slot init values ((name, fill), ...) threaded into every
        # rebuild so rows reborn in freed slots restart from the optimizer's
        # init (e.g. Adagrad initial accumulator), never a raw 0.
        self.slot_fills = tuple(slot_fills or ())
        # Overlapped-sync state (sync_async): one background IO round in
        # flight; `_pending` holds promotion candidates the worker found,
        # applied at the NEXT sync boundary. The worker never erases tier
        # rows — erasure decisions happen at apply time, so a discarded
        # round loses nothing. sync_stall_ms accumulates CALLER-side
        # blocking time; on_io is a test seam run in the worker before IO.
        self._worker: Optional[threading.Thread] = None
        self._worker_err: Optional[BaseException] = None
        self._pending: Optional[dict] = None
        self.sync_stall_ms: float = 0.0
        self.on_io = None
        # Tier-store serialization for the paging engine: the background
        # TierPrefetcher gather (probe_rows) may run CONCURRENTLY with the
        # tier-IO worker round or a training-thread boundary, and
        # HostKV/DiskKV have no internal synchronization. The RLock
        # serializes every store touch; the worker holds it for its whole
        # round (gathers simply land before or after the round), while the
        # training thread only ever takes it after _settle() — so it never
        # waits behind long IO, only behind one in-flight gather.
        self._store_lock = threading.RLock()
        # Tier revision: bumped at every boundary that can change store
        # contents (sync/sync_async/drain-with-erase/fold-erase/load).
        # It version-keys BOTH reuse surfaces: in-flight prefetch packages
        # (fold_candidates drops a package gathered at an older revision)
        # and the serving row cache below (the PR 17 discipline — a cached
        # row can never be served across a boundary that changed it).
        self._tier_rev = 0
        # Gather generation: the subset of revision bumps that make an
        # in-flight gather UNSAFE — boundaries that WRITE or replace tier
        # rows (demote at sync/sync_async, load). Pure erasures (fold /
        # promote erase) bump only `_tier_rev`: a package gathered before
        # an erase still holds bit-identical row content, and the fold's
        # device-freq revalidation rejects anything that trained since —
        # so folds don't retire each other's upcoming packages.
        self._gather_gen = 0
        # Fold erases deferred while a background round owns the stores;
        # drained (under the lock) at the next boundary, BEFORE the next
        # promote scan — which keeps the scan-diet invariant intact.
        self._pending_erase: list = []
        # Promote-scan diet: host∩device keys only arise when a demoted
        # key is looked up again (demotion removed it from the device), so
        # every promote candidate was TOUCHED since its demotion. Scanning
        # only rows with version >= the previous round's step — plus the
        # retry set of async/fold-ambiguous keys whose tier copy was
        # deliberately kept — is bit-identical to the full device-key scan.
        self.scan_diet = scan_diet
        self._scan_watermark: Optional[int] = None  # None = full scan
        self._retry_keys: set = set()
        # Paging-engine accounting (bench.py --tier-paging reads these).
        self.fold_stall_ms: float = 0.0
        self.folded_rows: int = 0
        self.fold_bytes: int = 0
        # Serving row cache: byte-bounded LRU over the D-wide value slice
        # of host/disk-resident rows, keyed (id, tier revision). Off by
        # default — lookup_with_fallback behaves exactly as before.
        self.row_cache = None
        if row_cache_bytes > 0:
            from deeprec_tpu.serving.reuse import ReuseCache

            self.row_cache = ReuseCache(
                int(row_cache_bytes), f"tier_rows_{cfg.name}",
                version_fn=lambda: self._tier_rev,
            )
        # obs plane: per-table tier movement counters + occupancy gauges
        # (table label = config name, a bounded set). No-op singletons
        # when DEEPREC_OBS=off.
        from deeprec_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.default_registry()
        lab = {"table": cfg.name}
        self._m_demoted = reg.counter(
            "deeprec_tier_demoted_rows", "device→host demotions", lab)
        self._m_promoted = reg.counter(
            "deeprec_tier_promoted_rows", "host/disk→device promotions",
            lab)
        self._m_spilled = reg.counter(
            "deeprec_tier_spilled_rows", "host→disk spills", lab)
        self._m_host_size = reg.gauge(
            "deeprec_tier_host_rows", "host-tier resident rows", lab)
        self._m_device_size = reg.gauge(
            "deeprec_tier_device_rows", "device-tier live rows", lab)
        self._m_stall = reg.gauge(
            "deeprec_tier_sync_stall_ms",
            "cumulative caller-side tier sync stall", lab)
        # Paging-engine counters (DRT007: the only label is the table
        # name, a bounded set fixed at construction).
        self._m_pf_probed = reg.counter(
            "deeprec_tier_prefetch_probed",
            "unique upcoming ids probed against the tier stores", lab)
        self._m_pf_hits = reg.counter(
            "deeprec_tier_prefetch_hits",
            "probed ids found resident in the host/disk tiers", lab)
        self._m_pf_folds = reg.counter(
            "deeprec_tier_prefetch_folds",
            "prefetched tier rows folded into the device table", lab)
        self._m_pf_stale = reg.counter(
            "deeprec_tier_prefetch_stale_dropped",
            "prefetched rows dropped by fold revalidation "
            "(stale revision or device row trained past the copy)", lab)
        self._m_pf_lag = reg.gauge(
            "deeprec_tier_prefetch_fold_lag_ms",
            "gather-to-fold latency of the last folded package", lab)

    def _publish_obs(self, stats: "TierStats") -> None:
        """Fold one sync round's TierStats into the obs plane — values
        the round already computed, no extra device traffic."""
        self._m_demoted.inc(stats.demoted)
        self._m_promoted.inc(stats.promoted)
        self._m_spilled.inc(stats.spilled)
        self._m_host_size.set(stats.host_size)
        self._m_device_size.set(stats.device_size)
        self._m_stall.set(self.sync_stall_ms)

    # --------------------------------------------------------- packed rows

    def _ensure_tiers(self, state: TableState) -> None:
        if self._slot_layout is not None:
            return
        cfg = self.table.cfg
        C = state.capacity
        from deeprec_tpu.optim.sparse import SCALAR_PREFIX

        # Per-row slots only (not table scalars), by NAME — shapes are
        # ambiguous under the packed small-dim layout, where a [C, w] slot
        # stores as [C // P, P * w]; the logical width is size // C.
        self._slot_layout = tuple(
            (name, int(np.prod(arr.shape)) // C)
            for name, arr in sorted(state.slots.items())
            if not name.startswith(SCALAR_PREFIX)
        )
        width = cfg.dim + sum(w for _, w in self._slot_layout)
        self._packed_dim = width
        if self.host is not None:  # pre-created by load(): widths must agree
            if self.host.dim != width:
                raise ValueError(
                    f"loaded tier rows are {self.host.dim} wide but this "
                    f"optimizer's packed layout needs {width} (values "
                    f"{cfg.dim} + slots {self._slot_layout}) — the spill "
                    "was written under a different optimizer"
                )
        else:
            self.host = HostKV(dim=width, initial_capacity=cfg.capacity)
        if self.disk is not None and self.disk.dim != width:
            raise ValueError(
                f"existing disk-tier log rows are {self.disk.dim} wide but "
                f"this optimizer's packed layout needs {width} — the log "
                "was written under a different optimizer"
            )
        if self.disk is None and (
            cfg.ev.storage.storage_type == StorageType.HBM_DRAM_SSD
        ):
            if self.storage_path:
                path = self.storage_path + ".ssd"
            else:
                # No explicit path -> a fresh private log per run. A fixed
                # default would silently resurrect a previous job's rows
                # (and hand them to promote as if they were this model's).
                import tempfile

                fd, path = tempfile.mkstemp(
                    prefix=f"deeprec_{cfg.name}_", suffix=".ssd"
                )
                os.close(fd)
            self.disk = DiskKV(path, width)

    def _pack_rows(self, state: TableState, row_ix: np.ndarray) -> np.ndarray:
        """[n, D + slot widths]: values then per-row slot columns (LOGICAL
        rows — packed small-dim storage unpacks via a free numpy view)."""
        from deeprec_tpu.ops.packed import unpack_array

        C = state.capacity
        cols = [
            unpack_array(np.asarray(state.values, np.float32), C)[row_ix]
        ]
        for name, w in self._slot_layout:
            arr = unpack_array(np.asarray(state.slots[name], np.float32), C)
            cols.append(arr[row_ix].reshape(len(row_ix), w))
        return np.concatenate(cols, axis=1)

    def _unpack_rows(self, state: TableState, row_ix: np.ndarray,
                     packed: np.ndarray) -> TableState:
        """Restore values AND per-row optimizer slots at row_ix."""
        from deeprec_tpu.ops.packed import scatter_rows_any

        D = self.table.cfg.dim
        C = state.capacity
        ix = jnp.asarray(row_ix, jnp.int32)
        state = state.replace(
            values=scatter_rows_any(
                state.values, ix, jnp.asarray(packed[:, :D], jnp.float32), C
            )
        )
        off = D
        slots = dict(state.slots)
        for name, w in self._slot_layout:
            chunk = jnp.asarray(packed[:, off:off + w], jnp.float32)
            slots[name] = scatter_rows_any(slots[name], ix, chunk, C)
            off += w
        return state.replace(slots=slots)

    # ------------------------------------------------------------------ sync

    def sync(self, state: TableState, step: int,
             slot_fills: Optional[tuple] = None,
             force: bool = False) -> tuple[TableState, TierStats]:
        """force=True demotes down to the low watermark even below the high
        watermark (capacity-pressure override: probes can exhaust from key
        clustering before occupancy reaches `high`), and always rebuilds —
        healing probe chains and resetting insert_fails — when there was
        nothing to demote."""
        stats = TierStats()
        # Serialize behind any in-flight background round: the worker owns
        # the tier stores while running (HostKV is not thread-safe), and
        # sync()'s own promote scan rediscovers anything the round found
        # (the worker never erases), so pending candidates simply drop.
        self._settle()
        # A dropped pending round's candidates were found by a scan whose
        # watermark already advanced; rediscovering them needs a FULL scan
        # this round, not the diet window.
        full_scan = self._pending is not None
        self._pending = None
        stats.spilled += self._take_spilled()
        self._drain_pending_erase()
        self._ensure_tiers(state)
        keys = np.asarray(state.keys)
        occ = keys != empty_key(self.table.cfg)
        freq = np.asarray(state.freq)
        version = np.asarray(state.version)

        # -------- promote: device rows re-created while a host (or disk)
        # copy exists. The scan diet restricts the probe to rows touched
        # since the last round (see __init__) — bit-identical outcomes,
        # O(window) native calls instead of O(device keys).
        occ_nz = np.nonzero(occ)[0]
        dev_keys_all = keys[occ].astype(np.int64)
        scan = self._scan_mask(dev_keys_all, version[occ],
                               self._take_retry(), self._scan_watermark,
                               full_scan)
        dev_keys = dev_keys_all[scan]
        if len(dev_keys):
            with self._store_lock:
                h_vals, h_freq, h_ver, found = self.host.get(dev_keys)
                if self.disk is not None and (~found).any():
                    # second-chance from the disk tier (disk hits re-enter
                    # the device directly; their disk record is dropped)
                    miss = ~found
                    d_vals, d_freq, d_ver, d_found = self.disk.get(
                        dev_keys[miss]
                    )
                    if d_found.any():
                        mix = np.nonzero(miss)[0][d_found]
                        h_vals[mix] = d_vals[d_found]
                        h_freq[mix] = d_freq[d_found]
                        h_ver[mix] = d_ver[d_found]
                        found[mix] = True
                        self.disk.erase(dev_keys[mix])
            dev_ix = occ_nz[scan][found]
            if dev_ix.size:
                hf = h_freq[found]
                hv = h_vals[found]
                hver = h_ver[found]
                df = freq[dev_ix]
                # freshly re-created rows have tiny device freq vs host freq
                refreshed = df <= hf
                if refreshed.any():
                    # packed host rows restore values AND optimizer slots
                    state = self._unpack_rows(
                        state, dev_ix[refreshed], hv[refreshed]
                    )
                    ix = jnp.asarray(dev_ix[refreshed], jnp.int32)
                    from deeprec_tpu.embedding.table import META_FREQ

                    state = state.replace(
                        meta=state.meta.at[META_FREQ, ix].add(
                            jnp.asarray(hf[refreshed], jnp.int32)
                        ),
                    )
                    stats.promoted = int(refreshed.sum())
                # either way the host copy is now stale: drop it
                with self._store_lock:
                    self.host.erase(dev_keys[found])

        # -------- demote: bring occupancy under the low watermark
        C = state.capacity
        live = int(occ.sum())
        threshold = int((self.low if force else self.high) * C)
        if live > threshold:
            n_out = live - int(self.low * C)
            occ_ix = np.nonzero(occ)[0]
            if self.cache_strategy == "lru":
                order = np.argsort(version[occ_ix])  # oldest-touched first
            else:  # lfu
                order = np.argsort(freq[occ_ix])  # coldest first
            out_ix = occ_ix[order[:n_out]]
            out_keys = keys[out_ix].astype(np.int64)
            packed = self._pack_rows(state, out_ix)
            with self._store_lock:
                self.host.put(out_keys, packed, freq[out_ix],
                              version[out_ix])
            keep = np.ones(C, bool)
            keep[out_ix] = False
            state = self.table.rebuild(
                state, keep=jnp.asarray(keep),
                slot_fills=tuple(slot_fills) if slot_fills else self.slot_fills,
            )
            stats.demoted = int(n_out)
        elif force:
            # Nothing to demote but the caller saw capacity pressure
            # (insert_fails from probe clustering): rebuild in place —
            # compacts probe chains and resets the fail counter so the
            # pressure signal reflects the healed table.
            state = self.table.rebuild(
                state,
                slot_fills=tuple(slot_fills) if slot_fills else self.slot_fills,
            )

        # -------- spill: bounded host tier overflows to the disk tier
        if (
            self.disk is not None
            and self.host_capacity
            and len(self.host) > self.host_capacity
        ):
            with self._store_lock:
                n_spill = len(self.host) - self.host_capacity
                ks, vs, fs, vers = self.host.export()
                order = (
                    np.argsort(vers) if self.cache_strategy == "lru"
                    else np.argsort(fs)
                )
                out = order[:n_spill]
                self.disk.put(ks[out], vs[out], fs[out], vers[out])
                self.host.erase(ks[out])
            stats.spilled += int(n_spill)

        stats.host_size = len(self.host)
        stats.device_size = int(self.table.size(state))
        if self.disk is not None:
            stats.disk_size = len(self.disk)
        # Boundary bookkeeping: the stores changed — retire in-flight
        # prefetch packages and cached serving rows, advance the diet
        # window (every promote candidate up to `step` was just resolved:
        # sync() erases every found tier copy, so no retry set survives).
        self._tier_rev += 1
        self._gather_gen += 1  # demotes WROTE rows — gathers are unsafe
        self._scan_watermark = int(step)
        self._publish_obs(stats)
        return state, stats

    # ------------------------------------------------------ overlapped sync

    def sync_async(self, state: TableState, step: int,
                   slot_fills: Optional[tuple] = None
                   ) -> tuple[TableState, TierStats]:
        """Overlapped tier migration: the caller pays only the device half
        (demote selection + packed-row gather + rebuild, all dispatched
        async; one live-count scalar read), while the HostKV/DiskKV IO —
        demoted-row puts, the promote scan, the disk spill — runs on a
        background thread that overlaps the next K-step dispatches.

        Double-buffered promotion: candidates the background round finds
        are applied at the NEXT sync_async/drain boundary, re-validated
        against the CURRENT device frequency so a key that trained past
        its host copy during the overlap window is never clobbered
        (ambiguous keys keep their tier copy and retry next round).
        Rounds serialize — entering a new round first drains the previous
        one. Not for concurrent use with lookup_with_fallback mid-round
        (serving readers must drain() first)."""
        t0 = time.perf_counter()
        stats = TierStats()
        self._ensure_tiers(state)
        state, stats.promoted = self._apply_pending(state)
        stats.spilled = self._take_spilled()  # last round's host->disk moves
        self._drain_pending_erase()  # fold erases deferred past the round

        C = state.capacity
        live = int(self.table.size(state))  # the one host-side scalar read
        demote_pkg = None
        if live > int(self.high * C):
            from deeprec_tpu.ops.compact import quantize_rows

            n_out = live - int(self.low * C)
            size = quantize_rows(n_out, C)
            ext = _demote_extract_jit(
                self.table, size, state, jnp.asarray(n_out, jnp.int32)
            )
            keep = ext.pop("keep")
            state = self.table.rebuild(
                state, keep=keep,
                slot_fills=tuple(slot_fills) if slot_fills else self.slot_fills,
            )
            demote_pkg = (ext, n_out)
            stats.demoted = n_out
        snap = _tier_snapshot_jit(state)
        # Sizes reflect the boundary, not the in-flight round — and must be
        # read BEFORE the worker starts mutating the (not thread-safe)
        # stores: demoted rows land in the host tier (and any spill
        # happens) while training runs, visible at the next boundary.
        stats.host_size = len(self.host)
        stats.device_size = live - stats.demoted
        if self.disk is not None:
            stats.disk_size = len(self.disk)
        # Boundary bookkeeping BEFORE the round starts: the worker is
        # about to mutate the stores, so any prefetch package gathered at
        # the old revision must die at its fold, and the diet window for
        # the round's scan is [previous watermark, step). The retry set is
        # consumed here on the training thread — the worker only reads
        # its own argument copy.
        self._tier_rev += 1
        self._gather_gen += 1  # the round demotes — gathers are unsafe
        retry = self._take_retry()
        watermark = self._scan_watermark
        self._scan_watermark = int(step)
        self._worker = threading.Thread(
            target=self._worker_main, args=(demote_pkg, snap, retry,
                                            watermark), daemon=True,
            name=f"tier-io-{self.table.cfg.name}-{step}",
        )
        self._worker.start()
        self.sync_stall_ms += (time.perf_counter() - t0) * 1e3
        self._publish_obs(stats)
        return state, stats

    def join(self) -> None:
        """Wait for the in-flight background round WITHOUT applying its
        promotions (shutdown/teardown). Pending candidates stay queued for
        the next boundary — nothing is lost: the worker never erases tier
        rows, so a discarded round leaves every copy where it was."""
        t = self._worker
        if t is not None:
            t.join()
            self._worker = None

    def _settle(self) -> None:
        """join() + surface a worker failure (the error-checked barrier
        every tier-store access goes through)."""
        self.join()
        err, self._worker_err = self._worker_err, None
        if err is not None:
            raise RuntimeError(f"tier IO worker failed: {err}") from err

    def _take_spilled(self) -> int:
        """Host->disk spill count of the last background round (the worker
        records it; TierStats surfaces it at the next boundary)."""
        n, self._spilled_bg = getattr(self, "_spilled_bg", 0), 0
        return n

    # ------------------------------------------------- paging coordination

    def _take_retry(self) -> np.ndarray:
        """Consume the ambiguous-key retry set (training thread only):
        keys whose tier copy was deliberately kept because the device row
        trained past it mid-flight. They re-enter exactly one scan — the
        one that consumes them — and re-add themselves if still ambiguous,
        so the set can never grow without bound."""
        taken, self._retry_keys = self._retry_keys, set()
        return np.fromiter(taken, np.int64, len(taken))

    def _scan_mask(self, occ_keys: np.ndarray, occ_version: np.ndarray,
                   retry: np.ndarray, watermark: Optional[int],
                   full: bool) -> np.ndarray:
        """Promote-scan diet filter over the occupied device keys: rows
        touched since `watermark` (version is the last-touched step,
        stamped at lookup) plus the retry set. Correctness: a tier copy
        for a device-resident key only exists because the key was looked
        up again AFTER its demotion — every candidate is window-touched
        or explicitly carried in `retry`."""
        if full or not self.scan_diet or watermark is None:
            return np.ones(len(occ_keys), bool)
        m = occ_version >= watermark
        if len(retry):
            m |= np.isin(occ_keys, retry)
        return m

    def _erase_tier_rows(self, keys: np.ndarray,
                         disk_keys: np.ndarray) -> None:
        """Erase folded (promoted) tier copies. While a background IO
        round owns the stores the erase is deferred to the next boundary
        — the training thread must never block behind the round's IO; the
        deferral keeps the copy visible a little longer, which fold
        revalidation already tolerates (a re-gathered copy loses to the
        now-fresher device row)."""
        if self._worker is not None and self._worker.is_alive():
            self._pending_erase.append((keys, disk_keys))
            return
        with self._store_lock:
            self.host.erase(keys)
            if self.disk is not None and len(disk_keys):
                self.disk.erase(disk_keys)
        self._tier_rev += 1

    def _drain_pending_erase(self) -> None:
        """Apply fold erases deferred past a background round. Runs at
        every boundary AFTER _settle()/_apply_pending and BEFORE the next
        promote scan, so a folded row's lingering tier copy never
        survives into the next round's candidate set."""
        if not self._pending_erase:
            return
        pend, self._pending_erase = self._pending_erase, []
        hk = np.concatenate([p[0] for p in pend])
        dk = np.concatenate([p[1] for p in pend])
        with self._store_lock:
            self.host.erase(hk)
            if self.disk is not None and len(dk):
                self.disk.erase(dk)
        self._tier_rev += 1

    def drain(self, state: TableState) -> tuple[TableState, TierStats]:
        """Finish the in-flight background round and apply its promotions
        now (checkpoint/serving boundaries). No-op when idle."""
        t0 = time.perf_counter()
        stats = TierStats()
        state, stats.promoted = self._apply_pending(state)
        stats.spilled = self._take_spilled()
        self._drain_pending_erase()
        stats.host_size = len(self.host) if self.host is not None else 0
        stats.device_size = int(self.table.size(state))
        if self.disk is not None:
            stats.disk_size = len(self.disk)
        self.sync_stall_ms += (time.perf_counter() - t0) * 1e3
        self._publish_obs(stats)
        return state, stats

    def _worker_main(self, demote_pkg, snap, retry, watermark) -> None:
        """Background IO round: put demoted rows, scan for promotion
        candidates against the post-rebuild key snapshot, spill host
        overflow. READ-only on promotion sources — erasure happens at
        apply time on the training thread. Holds `_store_lock` for the
        whole round: the only other store toucher while a round is in
        flight is the TierPrefetcher gather (probe_rows), which simply
        lands before or after the round; the training thread never takes
        the lock without `_settle()` first."""
        try:
            from deeprec_tpu.obs import trace as obs_trace

            t0w = time.time()
            if self.on_io is not None:
                self.on_io()  # test seam (ordering-based overlap tests)
            with self._store_lock:
                if demote_pkg is not None:
                    ext, n_out = demote_pkg
                    self.host.put(  # noqa: DRT004 — worker owns the tier stores until _settle(); every other path drains first
                        np.asarray(ext["keys"])[:n_out].astype(np.int64),
                        np.asarray(ext["rows"])[:n_out],
                        np.asarray(ext["freqs"])[:n_out],
                        np.asarray(ext["versions"])[:n_out],
                    )
                keys_snap = np.asarray(snap[0])
                freq_snap = np.asarray(snap[1])
                ver_snap = np.asarray(snap[2])  # noqa: DRT002 — snapshot copy read on the BACKGROUND worker, off the training thread by design
                occ = keys_snap != empty_key(self.table.cfg)
                dev_all = keys_snap[occ].astype(np.int64)
                scan = self._scan_mask(dev_all, ver_snap[occ], retry,
                                       watermark, False)
                dev_keys = dev_all[scan]
                pending = None
                if len(dev_keys):
                    h_vals, h_freq, h_ver, found = self.host.get(dev_keys)  # noqa: DRT004 — read-only promote scan under the same round-exclusive ownership
                    from_disk = np.zeros(len(dev_keys), bool)
                    if self.disk is not None and (~found).any():
                        miss = ~found
                        d_vals, d_freq, d_ver, d_found = self.disk.get(  # noqa: DRT004 — disk second-chance read, round-exclusive ownership
                            dev_keys[miss]
                        )
                        if d_found.any():
                            mix = np.nonzero(miss)[0][d_found]
                            h_vals[mix] = d_vals[d_found]
                            h_freq[mix] = d_freq[d_found]
                            h_ver[mix] = d_ver[d_found]
                            found[mix] = True
                            from_disk[mix] = True
                    if found.any():
                        pending = {
                            "keys": dev_keys[found],
                            "rows": h_vals[found],
                            "freqs": h_freq[found],
                            "snap_freq": freq_snap[occ][scan][found],
                            "from_disk": from_disk[found],
                        }
                self._pending = pending
                # spill: bounded host tier overflows to the disk tier
                if (
                    self.disk is not None
                    and self.host_capacity
                    and len(self.host) > self.host_capacity
                ):
                    n_spill = len(self.host) - self.host_capacity
                    ks, vs, fs, vers = self.host.export()  # noqa: DRT004 — spill export, round-exclusive ownership
                    order = (
                        np.argsort(vers) if self.cache_strategy == "lru"
                        else np.argsort(fs)
                    )
                    out = order[:n_spill]
                    self.disk.put(ks[out], vs[out], fs[out], vers[out])  # noqa: DRT004 — spill write, round-exclusive ownership
                    self.host.erase(ks[out])  # noqa: DRT004 — spill erase, round-exclusive ownership
                    self._spilled_bg = int(n_spill)
            # obs timeline span: one background tier-IO round (demote put
            # + promote scan + spill) — the "tier worker" track of the
            # training timeline. No-op unless DEEPREC_TRACE is set.
            obs_trace.phase_span("tier_io_round", t0w, time.time(),
                                 cat="train")
        except BaseException as e:
            self._worker_err = e

    def _apply_pending(self, state: TableState) -> tuple[TableState, int]:
        """Drain the worker and apply its promotion candidates, re-checked
        against the CURRENT device freq. Erasure rules (sync() parity, one
        round late): promoted -> tier copies dropped; device-already-newer
        at snapshot time -> stale copy dropped; ambiguous (device passed
        the host copy DURING the overlap) -> tier copy kept for the next
        round rather than clobbering fresh training."""
        from deeprec_tpu.ops.compact import quantize_rows

        self._settle()
        r, self._pending = self._pending, None
        if not r:
            return state, 0
        keys = r["keys"]
        n = len(keys)
        # pow2-bucketed probe so recurring applies reuse compiled shapes
        m = quantize_rows(n, state.capacity, floor=8)
        sent = empty_key(self.table.cfg)
        kp = np.full((m,), sent, np.dtype(state.keys.dtype))
        kp[:n] = keys
        from deeprec_tpu.embedding.table import probe_jit

        _, slot_ix, _, _ = probe_jit(
            self.table, state.keys, jnp.asarray(kp), jnp.zeros((m,), bool)
        )
        slot_ix = np.asarray(slot_ix)[:n]
        present = slot_ix >= 0
        freq_now = np.asarray(
            state.freq[jnp.asarray(np.where(present, slot_ix, 0))]
        )
        refreshed = present & (freq_now <= r["freqs"])
        stale = present & ~refreshed & (r["snap_freq"] > r["freqs"])
        k = int(refreshed.sum())
        if k:
            mm = quantize_rows(k, state.capacity, floor=8)
            ixp = np.full((mm,), -1, np.int32)
            ixp[:k] = slot_ix[refreshed]
            rowsp = np.zeros((mm, r["rows"].shape[1]), np.float32)
            rowsp[:k] = r["rows"][refreshed]
            fp = np.zeros((mm,), np.int32)
            fp[:k] = r["freqs"][refreshed]
            state = self._unpack_rows(state, ixp, rowsp)  # -1 rows skipped
            meta_ix = jnp.asarray(np.where(ixp >= 0, ixp, state.capacity))
            state = state.replace(
                meta=state.meta.at[META_FREQ, meta_ix].add(
                    jnp.asarray(fp), mode="drop"
                )
            )
        drop = refreshed | stale
        if drop.any():
            with self._store_lock:
                self.host.erase(keys[drop])
                if self.disk is not None and (r["from_disk"] & drop).any():
                    self.disk.erase(keys[r["from_disk"] & drop])
            self._tier_rev += 1
        # Ambiguous keys (device trained past the tier copy DURING the
        # overlap) keep their copy for the next round — the scan diet
        # would otherwise never revisit them once their touch window
        # passes, so they ride the retry set into exactly the next scan.
        ambiguous = present & ~drop
        if ambiguous.any():
            self._retry_keys.update(int(x) for x in keys[ambiguous])
        return state, k

    # ------------------------------------------------------ paging engine

    def probe_rows(self, ids) -> Optional[dict]:
        """Gather half of the demand-driven paging engine, called from the
        TierPrefetcher thread while upcoming batches still sit in the host
        prefetch queue: dedup the batch ids and gather any host/disk-
        resident packed rows (values + slots + freq). READ-only on the
        tier stores — a gather killed at any point leaves them untouched —
        and serialized against the tier-IO worker and training-thread
        boundaries by `_store_lock`.

        Returns None before anything was ever demoted or when nothing
        hit; otherwise a candidate package stamped with the gather-time
        GATHER GENERATION (`_gather_gen`). `fold_candidates` drops the
        whole package when a row-WRITING boundary (demote, load) ran in
        between — the PR 17 version-keyed reuse discipline applied to
        in-flight gathers. Pure erasures don't retire packages: their
        content is still bit-identical and fold revalidation rejects
        anything the device trained past."""
        if self.host is None and self.disk is None:
            return None
        uniq = np.unique(np.asarray(ids).reshape(-1).astype(np.int64))  # noqa: DRT002 — host batch ids on the PREFETCH thread, pre-device_put by design
        if not len(uniq):
            return None
        t0 = time.perf_counter()
        with self._store_lock:
            rev = self._gather_gen
            if self.host is not None:
                vals, freqs, vers, found = self.host.get(uniq)  # noqa: DRT004 — read-only gather under _store_lock; mutators hold the same lock
            else:
                vals = np.zeros((len(uniq), self.disk.dim), np.float32)
                freqs = np.zeros(len(uniq), np.int32)
                vers = np.zeros(len(uniq), np.int32)
                found = np.zeros(len(uniq), bool)
            vers = np.asarray(vers, np.int32).copy()  # noqa: DRT002 — host store metadata on the prefetch thread, no device sync
            from_disk = np.zeros(len(uniq), bool)
            if self.disk is not None and (~found).any():
                miss = ~found
                d_vals, d_freq, d_ver, d_found = self.disk.get(uniq[miss])  # noqa: DRT004 — read-only disk gather under _store_lock
                if d_found.any():
                    mix = np.nonzero(miss)[0][d_found]
                    vals[mix] = d_vals[d_found]
                    freqs[mix] = d_freq[d_found]
                    vers[mix] = d_ver[d_found]
                    found[mix] = True
                    from_disk[mix] = True
        self._m_pf_probed.inc(len(uniq))
        hits = int(found.sum())  # noqa: DRT002 — numpy reduction on the prefetch thread, no device sync
        if not hits:
            return None
        self._m_pf_hits.inc(hits)
        return {
            "keys": uniq[found],
            "rows": vals[found],
            "freqs": freqs[found],
            "vers": vers[found],
            "from_disk": from_disk[found],
            "rev": rev,
            "ts": t0,
        }

    def fold_candidates(self, state: TableState, cand: dict,
                        chunk: int = 256) -> tuple[TableState, int, int]:
        """Fold a gathered candidate package into the device table at a
        dispatch boundary (training thread). Candidates run through
        `_fold_chunk_jit` in fixed-size sentinel-padded chunks — ONE
        compiled shape per table, 0 steady-state compiles — where keys
        not yet device-resident are INSERTED with the tier copy (the row
        lands before the lookup that would have fresh-initialized it)
        and already-resident keys are revalidated against the CURRENT
        device freq before their values/slots scatter and freq merges
        (see _fold_chunk_jit).

        Folded rows' tier copies are erased (deferred past an in-flight
        background round); rows whose device copy trained past the tier
        copy are dropped and their keys ride the retry set into the next
        promote scan. A package gathered at an older gather generation is
        dropped whole — a demote/load WROTE rows under it. Returns
        (state, folded, dropped)."""
        t0 = time.perf_counter()
        n_all = len(cand["keys"])
        if cand["rev"] != self._gather_gen:
            # A demote/load wrote rows since the gather. The package's
            # CONTENT is dead, but its keys are a ready-made probe list:
            # re-gather them at the current generation (cheap numpy reads)
            # instead of losing the fold — unless a background round owns
            # the stores (the re-probe would stall the training thread for
            # the whole round; those keys come back via the post-boundary
            # requeue instead).
            idle = self._worker is None or not self._worker.is_alive()
            fresh = self.probe_rows(cand["keys"]) if idle else None
            if fresh is None:
                self._m_pf_stale.inc(n_all)
                return state, 0, n_all
            self._m_pf_stale.inc(n_all - len(fresh["keys"]))
            cand = fresh
            n_all = len(cand["keys"])
        self._ensure_tiers(state)
        keys = np.asarray(cand["keys"], np.int64)
        rows = np.asarray(cand["rows"], np.float32)
        freqs = np.asarray(cand["freqs"], np.int32)
        vers = np.asarray(
            cand.get("vers", np.zeros(n_all, np.int32)), np.int32
        )
        from_disk = np.asarray(cand["from_disk"], bool)
        sent = empty_key(self.table.cfg)
        kdtype = np.dtype(state.keys.dtype)
        folded = dropped = 0
        erase_h, erase_d = [], []
        for off in range(0, n_all, chunk):
            n = min(chunk, n_all - off)
            kp = np.full((chunk,), sent, kdtype)
            kp[:n] = keys[off:off + n]
            rp = np.zeros((chunk, rows.shape[1]), np.float32)
            rp[:n] = rows[off:off + n]
            fp = np.zeros((chunk,), np.int32)
            fp[:n] = freqs[off:off + n]
            vp = np.zeros((chunk,), np.int32)
            vp[:n] = vers[off:off + n]
            state, refreshed, present = _fold_chunk_jit(
                self.table, state, jnp.asarray(kp), jnp.asarray(rp),
                jnp.asarray(fp), jnp.asarray(vp),
            )
            refreshed = np.asarray(refreshed)[:n]
            present = np.asarray(present)[:n]
            folded += int(refreshed.sum())
            ambiguous = present & ~refreshed
            dropped += int(ambiguous.sum())
            if ambiguous.any():
                self._retry_keys.update(
                    int(x) for x in keys[off:off + n][ambiguous]
                )
            if refreshed.any():
                ck = keys[off:off + n]
                erase_h.append(ck[refreshed])
                erase_d.append(
                    ck[refreshed & from_disk[off:off + n]]
                )
        if folded:
            self._erase_tier_rows(
                np.concatenate(erase_h), np.concatenate(erase_d)
            )
            self._m_pf_folds.inc(folded)
            self._m_promoted.inc(folded)
            self.folded_rows += folded
            self.fold_bytes += folded * rows.shape[1] * 4
        if dropped:
            self._m_pf_stale.inc(dropped)
        self._m_pf_lag.set((t0 - cand["ts"]) * 1e3)
        self.fold_stall_ms += (time.perf_counter() - t0) * 1e3
        return state, folded, dropped

    def warm_fold(self, state: TableState, chunk: int = 256) -> None:
        """Pre-compile the fixed-chunk fold program for this table (warm
        phases — bench / serving bring-up): run one ALL-SENTINEL chunk
        through `_fold_chunk_jit`, a bit-exact no-op on the state (no key
        is real, so nothing inserts, scatters, or touches meta). After
        this, the first REAL fold pays zero compiles even when the first
        demote only lands inside the measured steady-state window."""
        self._ensure_tiers(state)
        sent = empty_key(self.table.cfg)
        kp = np.full((chunk,), sent, np.dtype(state.keys.dtype))
        rp = np.zeros((chunk, self._packed_dim), np.float32)
        zp = np.zeros((chunk,), np.int32)
        _fold_chunk_jit(
            self.table, state, jnp.asarray(kp), jnp.asarray(rp),
            jnp.asarray(zp), jnp.asarray(zp),
        )

    # ------------------------------------------------------------- serving

    def lookup_with_fallback(self, state: TableState, ids) -> jnp.ndarray:
        """Readonly lookup that also consults the host tier (then the disk
        tier) for misses — the serving-path equivalent of HbmDram's
        CopyEmbeddingsFromCPUToGPU.

        Ids are deduplicated before the native probe (one `get` over the
        uniques + inverse expand — a repeat-heavy bag stream pays one
        native call per DISTINCT row, not per position), and when the
        table was built with `row_cache_bytes` a byte-bounded LRU serves
        hot demoted rows without touching the stores at all. Cache
        entries are keyed (id, tier revision) — every boundary that can
        change a tier row bumps the revision, so a cached row is never
        served across a sync boundary that changed it. Both paths are
        bit-identical to the pre-dedup lookup."""
        self._settle()  # the worker owns the tier stores while a round runs
        emb = np.array(self.table.lookup_readonly(state, ids))  # writable copy
        if self.host is None and self.disk is None:  # nothing ever demoted
            return jnp.asarray(emb)
        D = self.table.cfg.dim
        flat_ids = np.asarray(ids).reshape(-1).astype(np.int64)
        uniq, inv = np.unique(flat_ids, return_inverse=True)
        n = len(uniq)
        u_vals = np.zeros((n, D), np.float32)
        u_found = np.zeros(n, bool)
        need = np.ones(n, bool)
        cache = self.row_cache
        if cache is not None:
            for j in range(n):
                hit = cache.get_current(
                    int(uniq[j]).to_bytes(8, "little", signed=True)
                )
                if hit is not None:
                    u_vals[j] = hit[0]
                    u_found[j] = True
                    need[j] = False
        probe = uniq[need]
        if len(probe):
            with self._store_lock:
                rev = self._tier_rev
                if self.host is not None:
                    h_vals, _, _, found = self.host.get(probe)
                else:
                    h_vals = np.zeros((len(probe), self.disk.dim), np.float32)
                    found = np.zeros(len(probe), bool)
                if self.disk is not None and (~found).any():
                    miss = ~found
                    d_vals, _, _, d_found = self.disk.get(probe[miss])
                    if d_found.any():
                        mix = np.nonzero(miss)[0][d_found]
                        h_vals[mix] = d_vals[d_found]
                        found[mix] = True
            if found.any():
                pix = np.nonzero(need)[0][found]
                rows = h_vals[found][:, :D]  # packed rows: values first
                u_vals[pix] = rows
                u_found[pix] = True
                if cache is not None:
                    for j, v in zip(pix, rows):
                        cache.put(
                            int(uniq[j]).to_bytes(8, "little", signed=True),
                            rev, np.array(v),
                        )
        if u_found.any():
            emb = emb.reshape(len(flat_ids), -1)
            sel = u_found[inv]
            emb[sel] = u_vals[inv[sel]]
            emb = emb.reshape(*np.asarray(ids).shape, -1)
        return jnp.asarray(emb)

    # ----------------------------------------------------------- spill/load

    def spill(self, path: Optional[str] = None) -> None:
        """Persist the host tier (and the disk tier's index)."""
        self._settle()  # never snapshot mid-round
        with self._store_lock:
            if self.host is not None:
                self.host.save(path or self.storage_path or "host_tier.bin")
            if self.disk is not None:
                self.disk.save()

    def load(self, path: Optional[str] = None) -> None:
        """Restore spilled tiers into a fresh instance (the serving flow —
        no sync() has run yet). A missing host spill is an empty tier (the
        writer may have spilled before anything was demoted); an existing
        disk log reopens using its header's row width. The first sync()
        validates both widths against the live optimizer's slot layout."""
        p = path or self.storage_path or "host_tier.bin"
        try:
            width = _spill_dim(p)
        except FileNotFoundError:
            width = None  # nothing was ever spilled: empty tier
        with self._store_lock:
            if width is not None:
                if self.host is None:
                    self.host = HostKV(
                        dim=width, initial_capacity=self.table.cfg.capacity
                    )
                self.host.load(p)
            if self.disk is None and self.storage_path:
                ssd = self.storage_path + ".ssd"
                if os.path.exists(ssd) and os.path.getsize(ssd) >= 8:
                    self.disk = DiskKV(ssd)  # width from the log header
        # Fresh store contents: retire cached rows / in-flight gathers and
        # force the next promote scan to run full (the touch history the
        # diet relies on did not travel with the spill).
        self._tier_rev += 1
        self._gather_gen += 1
        self._scan_watermark = None
        self._retry_keys = set()
