"""Multi-tier embedding storage: HBM working set + host-DRAM overflow.

DeepRec's HbmDramStorage (core/framework/embedding/hbm_dram_storage.h, cache
+ EvictionManager in cache.h/eviction_manager.h) keeps hot keys on the GPU
and migrates cold ones to DRAM with background threads. The TPU translation:
the device table IS the hot tier (fixed-capacity HBM arrays); a host-side
choreography step — run every `sync_every` steps, off the jitted hot path —
demotes cold rows (lowest-frequency LFU or oldest-version LRU) to the native
HostKV store and promotes host-resident rows whose keys reappeared on device.

Promotion correctness: when a demoted key is looked up again, the device
table creates a fresh slot with initializer values. sync() detects device
rows whose key exists in the host tier and whose device freq is LOWER than
the host freq — i.e. freshly re-created — and restores the host row
(values + optimizer slots are NOT in the host tier; DeepRec's DRAM tier
likewise stores values + stats, and optimizer slots restart. freq/version
merge so admission state survives the round-trip).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeprec_tpu.config import StorageType
from deeprec_tpu.embedding.table import EmbeddingTable, TableState, empty_key
from deeprec_tpu.native import HostKV


class DiskKV:
    """Log-structured on-disk row store — the SSD tier
    (dram_ssd_storage.h / ssd_hash_kv.h analog). Rows append to a flat
    record log (key, freq, version, value[dim]); an in-memory index maps
    key -> record offset, so updates are append+repoint and reads are one
    seek per key. `save()` persists the index sidecar; `load()` restores
    it (or rebuilds by scanning the log)."""

    def __init__(self, path: str, dim: int):
        import json as _json

        self.path = path
        self.dim = dim
        self.rec_bytes = 8 + 4 + 4 + 4 * dim
        self.index: dict = {}
        self._dtype = np.dtype(
            [("key", "<i8"), ("freq", "<i4"), ("ver", "<i4"),
             ("val", "<f4", (dim,))]
        )
        assert self._dtype.itemsize == self.rec_bytes
        mode = "r+b" if os.path.exists(path) else "w+b"
        self._f = open(path, mode)
        log_len = self._f.seek(0, 2)
        if log_len and os.path.exists(path + ".idx"):
            with open(path + ".idx") as f:
                saved = _json.load(f)
            self.index = {
                int(k): int(v) for k, v in saved.get("index", {}).items()
            }
            # A crash can leave records appended after the last save():
            # scan the tail past the sidecar's recorded length so those
            # keys (and updates) are not silently stale/lost.
            tail_from = int(saved.get("_len", 0))
            if log_len > tail_from:
                self._scan_index(tail_from)
        elif log_len:
            self._scan_index(0)

    def _scan_index(self, from_offset: int):
        """(Re)build index entries from log records at/after from_offset
        (later records win, log order)."""
        end = self._f.seek(0, 2)
        start = (from_offset // self.rec_bytes) * self.rec_bytes
        n = (end - start) // self.rec_bytes
        self._f.seek(start)
        recs = np.fromfile(self._f, self._dtype, n)
        for i, k in enumerate(recs["key"]):
            self.index[int(k)] = start + i * self.rec_bytes

    def __len__(self):
        return len(self.index)

    def put(self, keys, values, freqs=None, versions=None) -> None:
        n = len(keys)
        recs = np.zeros(n, self._dtype)
        recs["key"] = np.asarray(keys, np.int64)
        recs["freq"] = 0 if freqs is None else np.asarray(freqs, np.int32)
        recs["ver"] = 0 if versions is None else np.asarray(versions, np.int32)
        recs["val"] = np.asarray(values, np.float32).reshape(n, self.dim)
        self._f.seek(0, 2)
        base = self._f.tell()
        recs.tofile(self._f)
        self._f.flush()
        for i, k in enumerate(recs["key"]):
            self.index[int(k)] = base + i * self.rec_bytes

    def get(self, keys):
        keys = np.asarray(keys, np.int64)
        n = len(keys)
        vals = np.zeros((n, self.dim), np.float32)
        freqs = np.zeros(n, np.int32)
        vers = np.zeros(n, np.int32)
        found = np.zeros(n, bool)
        if not self.index or n == 0:
            return vals, freqs, vers, found
        # C-speed membership prefilter: sync() probes nearly every device
        # key here, while the disk tier usually holds few rows — only seek
        # for actual hits.
        idx_keys = np.fromiter(self.index.keys(), np.int64, len(self.index))
        hit_ix = np.nonzero(np.isin(keys, idx_keys))[0]
        for i in hit_ix:
            off = self.index[int(keys[i])]
            self._f.seek(off)
            rec = np.fromfile(self._f, self._dtype, 1)[0]
            vals[i] = rec["val"]
            freqs[i] = rec["freq"]
            vers[i] = rec["ver"]
            found[i] = True
        return vals, freqs, vers, found

    def erase(self, keys) -> None:
        for k in np.asarray(keys, np.int64):
            self.index.pop(int(k), None)

    def save(self) -> None:
        import json as _json

        self._f.flush()
        log_len = self._f.seek(0, 2)
        with open(self.path + ".idx", "w") as f:
            _json.dump({"_len": log_len, "index": self.index}, f)

    def close(self) -> None:
        self.save()
        self._f.close()


@dataclasses.dataclass
class TierStats:
    demoted: int = 0
    promoted: int = 0
    host_size: int = 0
    device_size: int = 0
    spilled: int = 0  # host -> disk this sync
    disk_size: int = 0


class MultiTierTable:
    """Wraps an EmbeddingTable with a host overflow tier.

    Usage: call `sync(state, step)` periodically from the host loop (e.g.
    every N steps or at checkpoint time). Lookup/apply stay the plain
    compiled table ops — the tier logic never touches the hot path, which is
    what makes this design TPU-viable.
    """

    def __init__(
        self,
        table: EmbeddingTable,
        high_watermark: float = 0.8,
        low_watermark: float = 0.6,
        storage_path: Optional[str] = None,
        slot_fills: Optional[tuple] = None,
    ):
        cfg = table.cfg
        self.table = table
        self.high = high_watermark
        self.low = low_watermark
        self.host = HostKV(dim=cfg.dim, initial_capacity=cfg.capacity)
        self.cache_strategy = cfg.ev.storage.cache_strategy
        self.storage_path = storage_path or cfg.ev.storage.storage_path
        # third tier (HBM_DRAM_SSD): bounded host DRAM, coldest rows spill
        # to a log-structured disk store (storage-factory combo semantics,
        # reference storage_factory.h / hbm_dram_ssd_storage.h)
        self.host_capacity = cfg.ev.storage.host_capacity
        self.disk: Optional[DiskKV] = None
        if cfg.ev.storage.storage_type == StorageType.HBM_DRAM_SSD:
            if self.storage_path:
                path = self.storage_path + ".ssd"
            else:
                # No explicit path -> a fresh private log per run. A fixed
                # default would silently resurrect a previous job's rows
                # (and hand them to promote as if they were this model's).
                import tempfile

                fd, path = tempfile.mkstemp(
                    prefix=f"deeprec_{cfg.name}_", suffix=".ssd"
                )
                os.close(fd)
            self.disk = DiskKV(path, cfg.dim)
        # Optimizer slot init values ((name, fill), ...) threaded into every
        # rebuild so rows reborn in freed slots restart from the optimizer's
        # init (e.g. Adagrad initial accumulator), never a raw 0.
        self.slot_fills = tuple(slot_fills or ())

    # ------------------------------------------------------------------ sync

    def sync(self, state: TableState, step: int,
             slot_fills: Optional[tuple] = None,
             force: bool = False) -> tuple[TableState, TierStats]:
        """force=True demotes down to the low watermark even below the high
        watermark (capacity-pressure override: probes can exhaust from key
        clustering before occupancy reaches `high`), and always rebuilds —
        healing probe chains and resetting insert_fails — when there was
        nothing to demote."""
        stats = TierStats()
        keys = np.asarray(state.keys)
        occ = keys != empty_key(self.table.cfg)
        freq = np.asarray(state.freq)
        version = np.asarray(state.version)

        # -------- promote: device rows re-created while a host (or disk)
        # copy exists
        dev_keys = keys[occ].astype(np.int64)
        if len(dev_keys):
            h_vals, h_freq, h_ver, found = self.host.get(dev_keys)
            if self.disk is not None and (~found).any():
                # second-chance from the disk tier (disk hits re-enter the
                # device directly; their disk record is dropped)
                miss = ~found
                d_vals, d_freq, d_ver, d_found = self.disk.get(dev_keys[miss])
                if d_found.any():
                    mix = np.nonzero(miss)[0][d_found]
                    h_vals[mix] = d_vals[d_found]
                    h_freq[mix] = d_freq[d_found]
                    h_ver[mix] = d_ver[d_found]
                    found[mix] = True
                    self.disk.erase(dev_keys[mix])
            dev_ix = np.nonzero(occ)[0][found]
            if dev_ix.size:
                hf = h_freq[found]
                hv = h_vals[found]
                hver = h_ver[found]
                df = freq[dev_ix]
                # freshly re-created rows have tiny device freq vs host freq
                refreshed = df <= hf
                if refreshed.any():
                    ix = jnp.asarray(dev_ix[refreshed], jnp.int32)
                    state = state.replace(
                        values=state.values.at[ix].set(
                            jnp.asarray(hv[refreshed], state.values.dtype)
                        ),
                        freq=state.freq.at[ix].add(
                            jnp.asarray(hf[refreshed], jnp.int32)
                        ),
                    )
                    stats.promoted = int(refreshed.sum())
                # either way the host copy is now stale: drop it
                self.host.erase(dev_keys[found])

        # -------- demote: bring occupancy under the low watermark
        C = state.capacity
        live = int(occ.sum())
        threshold = int((self.low if force else self.high) * C)
        if live > threshold:
            n_out = live - int(self.low * C)
            occ_ix = np.nonzero(occ)[0]
            if self.cache_strategy == "lru":
                order = np.argsort(version[occ_ix])  # oldest-touched first
            else:  # lfu
                order = np.argsort(freq[occ_ix])  # coldest first
            out_ix = occ_ix[order[:n_out]]
            out_keys = keys[out_ix].astype(np.int64)
            self.host.put(
                out_keys,
                np.asarray(state.values)[out_ix],
                freq[out_ix],
                version[out_ix],
            )
            keep = np.ones(C, bool)
            keep[out_ix] = False
            state = self.table.rebuild(
                state, keep=jnp.asarray(keep),
                slot_fills=tuple(slot_fills) if slot_fills else self.slot_fills,
            )
            stats.demoted = int(n_out)
        elif force:
            # Nothing to demote but the caller saw capacity pressure
            # (insert_fails from probe clustering): rebuild in place —
            # compacts probe chains and resets the fail counter so the
            # pressure signal reflects the healed table.
            state = self.table.rebuild(
                state,
                slot_fills=tuple(slot_fills) if slot_fills else self.slot_fills,
            )

        # -------- spill: bounded host tier overflows to the disk tier
        if (
            self.disk is not None
            and self.host_capacity
            and len(self.host) > self.host_capacity
        ):
            n_spill = len(self.host) - self.host_capacity
            ks, vs, fs, vers = self.host.export()
            order = (
                np.argsort(vers) if self.cache_strategy == "lru"
                else np.argsort(fs)
            )
            out = order[:n_spill]
            self.disk.put(ks[out], vs[out], fs[out], vers[out])
            self.host.erase(ks[out])
            stats.spilled = int(n_spill)

        stats.host_size = len(self.host)
        stats.device_size = int(self.table.size(state))
        if self.disk is not None:
            stats.disk_size = len(self.disk)
        return state, stats

    # ------------------------------------------------------------- serving

    def lookup_with_fallback(self, state: TableState, ids) -> jnp.ndarray:
        """Readonly lookup that also consults the host tier (then the disk
        tier) for misses — the serving-path equivalent of HbmDram's
        CopyEmbeddingsFromCPUToGPU."""
        emb = np.array(self.table.lookup_readonly(state, ids))  # writable copy
        flat_ids = np.asarray(ids).reshape(-1).astype(np.int64)
        h_vals, _, _, found = self.host.get(flat_ids)
        if self.disk is not None and (~found).any():
            miss = ~found
            d_vals, _, _, d_found = self.disk.get(flat_ids[miss])
            if d_found.any():
                mix = np.nonzero(miss)[0][d_found]
                h_vals[mix] = d_vals[d_found]
                found[mix] = True
        if found.any():
            emb = emb.reshape(len(flat_ids), -1)
            emb[found] = h_vals[found]
            emb = emb.reshape(*np.asarray(ids).shape, -1)
        return jnp.asarray(emb)

    # ----------------------------------------------------------- spill/load

    def spill(self, path: Optional[str] = None) -> None:
        """Persist the host tier (and the disk tier's index)."""
        self.host.save(path or self.storage_path or "host_tier.bin")
        if self.disk is not None:
            self.disk.save()

    def load(self, path: Optional[str] = None) -> None:
        self.host.load(path or self.storage_path or "host_tier.bin")
