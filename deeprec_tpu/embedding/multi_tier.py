"""Multi-tier embedding storage: HBM working set + host-DRAM overflow.

DeepRec's HbmDramStorage (core/framework/embedding/hbm_dram_storage.h, cache
+ EvictionManager in cache.h/eviction_manager.h) keeps hot keys on the GPU
and migrates cold ones to DRAM with background threads. The TPU translation:
the device table IS the hot tier (fixed-capacity HBM arrays); a host-side
choreography step — run every `sync_every` steps, off the jitted hot path —
demotes cold rows (lowest-frequency LFU or oldest-version LRU) to the native
HostKV store and promotes host-resident rows whose keys reappeared on device.

Promotion correctness: when a demoted key is looked up again, the device
table creates a fresh slot with initializer values. sync() detects device
rows whose key exists in the host tier and whose device freq is LOWER than
the host freq — i.e. freshly re-created — and restores the host row
(values + optimizer slots are NOT in the host tier; DeepRec's DRAM tier
likewise stores values + stats, and optimizer slots restart. freq/version
merge so admission state survives the round-trip).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeprec_tpu.config import StorageType
from deeprec_tpu.embedding.table import EmbeddingTable, TableState, empty_key
from deeprec_tpu.native import HostKV


@dataclasses.dataclass
class TierStats:
    demoted: int = 0
    promoted: int = 0
    host_size: int = 0
    device_size: int = 0


class MultiTierTable:
    """Wraps an EmbeddingTable with a host overflow tier.

    Usage: call `sync(state, step)` periodically from the host loop (e.g.
    every N steps or at checkpoint time). Lookup/apply stay the plain
    compiled table ops — the tier logic never touches the hot path, which is
    what makes this design TPU-viable.
    """

    def __init__(
        self,
        table: EmbeddingTable,
        high_watermark: float = 0.8,
        low_watermark: float = 0.6,
        storage_path: Optional[str] = None,
        slot_fills: Optional[tuple] = None,
    ):
        cfg = table.cfg
        self.table = table
        self.high = high_watermark
        self.low = low_watermark
        self.host = HostKV(dim=cfg.dim, initial_capacity=cfg.capacity)
        self.cache_strategy = cfg.ev.storage.cache_strategy
        self.storage_path = storage_path or cfg.ev.storage.storage_path
        # Optimizer slot init values ((name, fill), ...) threaded into every
        # rebuild so rows reborn in freed slots restart from the optimizer's
        # init (e.g. Adagrad initial accumulator), never a raw 0.
        self.slot_fills = tuple(slot_fills or ())

    # ------------------------------------------------------------------ sync

    def sync(self, state: TableState, step: int,
             slot_fills: Optional[tuple] = None,
             force: bool = False) -> tuple[TableState, TierStats]:
        """force=True demotes down to the low watermark even below the high
        watermark (capacity-pressure override: probes can exhaust from key
        clustering before occupancy reaches `high`), and always rebuilds —
        healing probe chains and resetting insert_fails — when there was
        nothing to demote."""
        stats = TierStats()
        keys = np.asarray(state.keys)
        occ = keys != empty_key(self.table.cfg)
        freq = np.asarray(state.freq)
        version = np.asarray(state.version)

        # -------- promote: device rows re-created while a host copy exists
        dev_keys = keys[occ].astype(np.int64)
        if len(dev_keys):
            h_vals, h_freq, h_ver, found = self.host.get(dev_keys)
            dev_ix = np.nonzero(occ)[0][found]
            if dev_ix.size:
                hf = h_freq[found]
                hv = h_vals[found]
                hver = h_ver[found]
                df = freq[dev_ix]
                # freshly re-created rows have tiny device freq vs host freq
                refreshed = df <= hf
                if refreshed.any():
                    ix = jnp.asarray(dev_ix[refreshed], jnp.int32)
                    state = state.replace(
                        values=state.values.at[ix].set(
                            jnp.asarray(hv[refreshed], state.values.dtype)
                        ),
                        freq=state.freq.at[ix].add(
                            jnp.asarray(hf[refreshed], jnp.int32)
                        ),
                    )
                    stats.promoted = int(refreshed.sum())
                # either way the host copy is now stale: drop it
                self.host.erase(dev_keys[found])

        # -------- demote: bring occupancy under the low watermark
        C = state.capacity
        live = int(occ.sum())
        threshold = int((self.low if force else self.high) * C)
        if live > threshold:
            n_out = live - int(self.low * C)
            occ_ix = np.nonzero(occ)[0]
            if self.cache_strategy == "lru":
                order = np.argsort(version[occ_ix])  # oldest-touched first
            else:  # lfu
                order = np.argsort(freq[occ_ix])  # coldest first
            out_ix = occ_ix[order[:n_out]]
            out_keys = keys[out_ix].astype(np.int64)
            self.host.put(
                out_keys,
                np.asarray(state.values)[out_ix],
                freq[out_ix],
                version[out_ix],
            )
            keep = np.ones(C, bool)
            keep[out_ix] = False
            state = self.table.rebuild(
                state, keep=jnp.asarray(keep),
                slot_fills=tuple(slot_fills) if slot_fills else self.slot_fills,
            )
            stats.demoted = int(n_out)
        elif force:
            # Nothing to demote but the caller saw capacity pressure
            # (insert_fails from probe clustering): rebuild in place —
            # compacts probe chains and resets the fail counter so the
            # pressure signal reflects the healed table.
            state = self.table.rebuild(
                state,
                slot_fills=tuple(slot_fills) if slot_fills else self.slot_fills,
            )

        stats.host_size = len(self.host)
        stats.device_size = int(self.table.size(state))
        return state, stats

    # ------------------------------------------------------------- serving

    def lookup_with_fallback(self, state: TableState, ids) -> jnp.ndarray:
        """Readonly lookup that also consults the host tier for misses —
        the serving-path equivalent of HbmDram's CopyEmbeddingsFromCPUToGPU."""
        emb = np.array(self.table.lookup_readonly(state, ids))  # writable copy
        flat_ids = np.asarray(ids).reshape(-1).astype(np.int64)
        h_vals, _, _, found = self.host.get(flat_ids)
        if found.any():
            emb = emb.reshape(len(flat_ids), -1)
            emb[found] = h_vals[found]
            emb = emb.reshape(*np.asarray(ids).shape, -1)
        return jnp.asarray(emb)

    # ----------------------------------------------------------- spill/load

    def spill(self, path: Optional[str] = None) -> None:
        """Persist the host tier (the SSD/LevelDB-tier analog)."""
        self.host.save(path or self.storage_path or "host_tier.bin")

    def load(self, path: Optional[str] = None) -> None:
        self.host.load(path or self.storage_path or "host_tier.bin")
