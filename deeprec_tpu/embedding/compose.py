"""Composite embedding schemes: multi-hash compression and adaptive
static+dynamic lookup.

Parity targets:
  * tf.get_multihash_variable (reference tensorflow/python/ops/
    variable_scope.py:1642 / kv_variable_ops.py MultiHashVariable): the
    quotient–remainder trick — two small tables indexed by complementary
    hashes of the id, combined (add/mul/concat) into one embedding. O(sqrt V)
    memory for a V-sized vocabulary at the cost of controlled collisions.
  * tf.nn.adaptive_embedding_lookup_sparse (embedding_ops.py:667): ids are
    dynamically partitioned between a compact static bucketed table (cheap,
    collisions allowed — the long tail) and the exact hash table (hot,
    important ids), by observed frequency.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deeprec_tpu.embedding.table import EmbeddingTable, TableState
from deeprec_tpu.utils import hashing


@dataclasses.dataclass(frozen=True)
class MultiHashConfig:
    name: str
    dim: int
    num_buckets_q: int  # quotient table rows (power of two)
    num_buckets_r: int  # remainder table rows (power of two)
    strategy: str = "add"  # add | mul | concat


class MultiHashTable:
    """Quotient–remainder composed embedding. Both component tables are
    ordinary dense arrays (every bucket always exists — no admission), so
    this is a pure-compute lookup fully fused by XLA."""

    def __init__(self, cfg: MultiHashConfig):
        self.cfg = cfg
        if cfg.strategy not in ("add", "mul", "concat"):
            raise ValueError(cfg.strategy)

    @property
    def dim(self) -> int:
        d = self.cfg.dim
        return 2 * d if self.cfg.strategy == "concat" else d

    def create(self, key) -> Tuple[jnp.ndarray, jnp.ndarray]:
        kq, kr = jax.random.split(key)
        d = self.cfg.dim
        q = jax.random.normal(kq, (self.cfg.num_buckets_q, d)) * 0.05
        r = jax.random.normal(kr, (self.cfg.num_buckets_r, d)) * 0.05
        return q, r

    def lookup(self, params: Tuple[jnp.ndarray, jnp.ndarray], ids: jnp.ndarray):
        q_tab, r_tab = params
        Q = self.cfg.num_buckets_q
        R = self.cfg.num_buckets_r
        qi = (ids.astype(jnp.uint32) // jnp.uint32(R)) % jnp.uint32(Q)
        ri = ids.astype(jnp.uint32) % jnp.uint32(R)
        eq = q_tab[qi.astype(jnp.int32)]
        er = r_tab[ri.astype(jnp.int32)]
        if self.cfg.strategy == "add":
            return eq + er
        if self.cfg.strategy == "mul":
            return eq * er
        return jnp.concatenate([eq, er], axis=-1)


class DynamicDimEmbedding:
    """Frequency-tiered embedding dimension.

    Parity: tf.get_dynamic_dimension_embedding_variable
    (variable_scope.py:2372, dynamic_dim_feature_descriptor_impl.h): rare
    keys train only a prefix of the embedding vector; the dimension steps up
    with observed frequency. TPU translation: storage stays the full [C, D]
    array (static shapes), but lookups MASK the tail dims of low-frequency
    keys to zero — gradients to masked dims are zeroed by the same mask in
    the backward (chain rule through the multiply), so those dims neither
    train nor serve until the key graduates. The statistical effect (tail
    keys get low-capacity vectors) is preserved; HBM savings come from
    pairing with multi-tier demotion rather than ragged rows.
    """

    def __init__(self, table: EmbeddingTable, dim_tiers, freq_tiers):
        """dim_tiers: ascending dims, e.g. (8, 16, 32) with full dim last;
        freq_tiers: thresholds, len = len(dim_tiers) - 1: keys with
        freq < freq_tiers[0] use dim_tiers[0], etc."""
        assert len(dim_tiers) == len(freq_tiers) + 1
        assert dim_tiers[-1] == table.cfg.dim
        self.table = table
        self.dim_tiers = tuple(dim_tiers)
        self.freq_tiers = tuple(freq_tiers)

    def effective_dim(self, state: TableState, res) -> jnp.ndarray:
        present = res.slot_ix >= 0
        safe_ix = jnp.where(present, res.slot_ix, 0)
        # absent/blocked keys must not inherit slot 0's frequency: tier 0
        freq = jnp.where(present, state.freq.at[safe_ix].get(mode="clip"), 0)
        dim = jnp.full(freq.shape, self.dim_tiers[0], jnp.int32)
        for d, thr in zip(self.dim_tiers[1:], self.freq_tiers):
            dim = jnp.where(freq >= thr, d, dim)
        return dim

    def lookup_unique(self, state: TableState, ids, *, step=0, train=True,
                      pad_value=-1):
        state, res = self.table.lookup_unique(
            state, ids, step=step, train=train, pad_value=pad_value
        )
        eff = self.effective_dim(state, res)  # [U]
        col = jax.lax.broadcasted_iota(jnp.int32, res.embeddings.shape, 1)
        masked = jnp.where(col < eff[:, None], res.embeddings, 0.0)
        return state, res.replace(embeddings=masked)


class AdaptiveEmbedding:
    """Frequency-adaptive routing between a static bucketed table and the
    exact hash table.

    lookup(): ids admitted by the hash table (frequency >= the table's
    counter-filter threshold, or simply present) read exact embeddings; the
    rest read a hash-bucketed static row. The static table absorbs the long
    tail at fixed memory; the hash table gives head ids exact, evictable,
    checkpointable embeddings — the adaptive_embedding_lookup semantics with
    the dynamic_partition replaced by a masked select.
    """

    def __init__(self, table: EmbeddingTable, static_buckets: int = 1 << 14):
        assert static_buckets & (static_buckets - 1) == 0
        self.table = table
        self.static_buckets = static_buckets

    def create_static(self, key) -> jnp.ndarray:
        return jax.random.normal(key, (self.static_buckets, self.table.cfg.dim)) * 0.05

    def lookup_unique(self, state: TableState, static_tab, ids, *, step=0,
                      train=True, pad_value=-1):
        state, res = self.table.lookup_unique(
            state, ids, step=step, train=train, pad_value=pad_value
        )
        bucket = hashing.hash_to_bucket(res.uids, self.static_buckets, salt=0xADA)
        e_static = static_tab[bucket]
        use_exact = res.admitted[:, None]
        emb = jnp.where(use_exact, res.embeddings, e_static.astype(res.embeddings.dtype))
        return state, res.replace(embeddings=emb), use_exact[:, 0]

    def grads(self, res, use_exact, grad_u):
        """Split upstream grads: exact-path rows go to the hash table's
        sparse apply, static-path rows return (bucket_ix, grads) for a dense
        scatter-add by the caller's optimizer."""
        g_exact = jnp.where(use_exact[:, None], grad_u, 0.0)
        g_static = jnp.where(use_exact[:, None], 0.0, grad_u)
        bucket = hashing.hash_to_bucket(res.uids, self.static_buckets, salt=0xADA)
        return g_exact, (bucket, g_static)
