"""Demand-driven tier paging: background probe/gather pump for upcoming ids.

The maintain()-cadence promote scan (multi_tier.py) restores a demoted
row only at the NEXT sync boundary — a demoted key that reappears
mid-window trains from a fresh re-init until then, losing the optimizer
state its host/disk copy still holds. This module closes that window:
while batches sit in the host `Prefetcher` queue (before `device_put`),
a background thread probes their ids against the tier key indexes and
gathers resident packed rows (`MultiTierTable.probe_rows`); the training
thread folds the gathered rows in at the next dispatch boundary through
one fixed-chunk compiled promote program (`fold_candidates` /
`_fold_chunk_jit`), revalidated against the current device freq so a row
that trained past its tier copy mid-flight is never clobbered.

Ownership protocol (DRT004): ONE background thread (`tier-prefetch`)
owns the probe/gather half — it is the only caller of `probe_rows`,
whose store reads serialize against the tier-IO worker and the training
thread under each table's `_store_lock`. The training thread owns
`take`/`pending_keys` (and the folds). The pending map is the only state
shared between the two and every touch goes through `self._lock`; the
batch queue hand-off goes through `self._cv`. Gathers are READ-only on
the tier stores, so killing the pump mid-gather (close(), or a gather
error) can never leave the stores inconsistent — the next maintain scan
simply rediscovers whatever was never folded.

docs/multi-tier-storage.md "Overlapped tier paging" is the contract;
bench.py --tier-paging measures it and roofline.py --assert-tier gates
it.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class TierPrefetcher:
    """Background id-probe/row-gather pump feeding dispatch-boundary folds.

    resolve: key -> MultiTierTable | None (None = that member has no tier
        yet — nothing was ever demoted, nothing to page).
    extract: host batch -> {key: flat id array} for every multi-tier
        member (runs on the PUMP thread, so producer-side observe() stays
        O(1): it only enqueues a batch reference).
    depth: observed-batch queue bound; when the pump falls behind, the
        OLDEST unprobed batch drops (best-effort — a dropped probe only
        delays a fold to the next maintain scan, never loses data).
    max_pending: per-member bound on buffered candidate rows; beyond it
        new gathers drop (counted) until a fold drains the buffer.
    """

    def __init__(
        self,
        resolve: Callable[[Tuple], Any],
        extract: Callable[[Dict[str, np.ndarray]], Dict[Tuple, np.ndarray]],
        depth: int = 4,
        max_pending: int = 8192,
    ):
        self.resolve = resolve
        self.extract = extract
        self.max_pending = int(max_pending)
        self._q: deque = deque(maxlen=max(1, int(depth)))
        # last few probed batches, kept for requeue_recent(): a store-
        # writing boundary (demote) invalidates their gathers AND may
        # have demoted rows they are about to look up — re-probing the
        # pipeline window catches both.
        self._recent: deque = deque(maxlen=max(1, int(depth)))
        self._cv = threading.Condition()
        self._busy = False
        self._lock = threading.Lock()
        # key -> {"rev": gather-time tier revision, "ts": oldest gather
        # time, "rows": {id: (packed row, freq, ver, from_disk)}} — later
        # gathers for the same id win (the store row cannot have changed
        # at the same revision, so this is a dedup, not a race).
        self._pending: Dict[Tuple, dict] = {}
        self._stop = threading.Event()
        self.dropped_batches = 0
        self.dropped_rows = 0
        self.gather_errors = 0
        self.last_error: Optional[BaseException] = None
        self.on_gather = None  # test seam: called on the pump thread per batch
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tier-prefetch"
        )
        self._thread.start()

    # ------------------------------------------------------- producer side

    def observe(self, batch: Dict[str, np.ndarray]) -> None:
        """Prefetcher `peek` hook (producer thread): hand the raw host
        batch to the pump. Never blocks, never raises — a full queue
        drops the oldest unprobed batch."""
        if self._stop.is_set():
            return
        with self._cv:
            if len(self._q) == self._q.maxlen:
                self.dropped_batches += 1
            self._q.append(batch)
            self._cv.notify()

    # ----------------------------------------------------------- pump loop

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop.is_set():
                    self._cv.wait(0.1)
                if self._stop.is_set():
                    return
                batch = self._q.popleft()
                self._recent.append(batch)
                self._busy = True
            try:
                if self.on_gather is not None:
                    self.on_gather(batch)
                for key, ids in self.extract(batch).items():
                    mt = self.resolve(key)
                    if mt is None:
                        continue
                    cand = mt.probe_rows(ids)
                    if cand is not None:
                        self._merge(key, cand)
            except BaseException as e:  # a failed gather must not kill the pump
                self.gather_errors += 1
                self.last_error = e
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _merge(self, key: Tuple, cand: dict) -> None:
        with self._lock:
            cur = self._pending.get(key)
            if cur is None or cur["rev"] != cand["rev"]:
                # A generation bump means a row-WRITING boundary (demote,
                # load) mutated the stores: everything buffered at the old
                # generation is dead content — replace instead of merging
                # (the fold re-probes a stale package's keys itself, but a
                # fresh gather is already here: don't mix generations).
                cur = {"rev": cand["rev"], "ts": cand["ts"], "rows": {}}
                self._pending[key] = cur
            rows = cur["rows"]
            cur["ts"] = min(cur["ts"], cand["ts"])
            vers = cand.get("vers")
            for i, k in enumerate(cand["keys"]):
                k = int(k)  # noqa: DRT002 — host numpy scalar on the pump thread, no device sync
                if len(rows) >= self.max_pending and k not in rows:
                    self.dropped_rows += 1
                    continue
                rows[k] = (
                    cand["rows"][i], int(cand["freqs"][i]),  # noqa: DRT002 — host numpy scalar on the pump thread
                    int(vers[i]) if vers is not None else 0,  # noqa: DRT002 — host numpy scalar on the pump thread
                    bool(cand["from_disk"][i]),
                )

    def requeue_recent(self) -> None:
        """Re-enqueue the recently probed batches (training thread, after
        a store-WRITING boundary like maintain's demote): the boundary
        retired their gathered packages and may have demoted rows they
        are about to look up — re-probing the pipeline window lets the
        fold still land before those lookups. Never blocks."""
        if self._stop.is_set():
            return
        with self._cv:
            for b in list(self._recent):
                if len(self._q) == self._q.maxlen:
                    self.dropped_batches += 1
                self._q.append(b)
            self._cv.notify()

    # ------------------------------------------------------ consumer side

    def pending_keys(self) -> list:
        """Members with buffered candidates (training thread)."""
        with self._lock:
            return [k for k, v in self._pending.items() if v["rows"]]

    def take(self, key: Tuple) -> Optional[dict]:
        """Pop the merged candidate package for one member (training
        thread) — the argument `MultiTierTable.fold_candidates` takes."""
        with self._lock:
            cur = self._pending.pop(key, None)
        if not cur or not cur["rows"]:
            return None
        items = list(cur["rows"].items())
        return {
            "keys": np.asarray([k for k, _ in items], np.int64),
            "rows": np.stack([v[0] for _, v in items]),
            "freqs": np.asarray([v[1] for _, v in items], np.int32),
            "vers": np.asarray([v[2] for _, v in items], np.int32),
            "from_disk": np.asarray([v[3] for _, v in items], bool),
            "rev": cur["rev"],
            "ts": cur["ts"],
        }

    # ----------------------------------------------------------- lifecycle

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until every observed batch has been probed (tests and
        bench boundaries — folds then see a deterministic candidate set).
        True = idle; False = timed out with work still in flight."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._q or self._busy:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.05))
        return True

    def close(self) -> None:
        """Stop the pump thread. Safe mid-gather: probes are read-only on
        the tier stores, so whatever the in-flight gather touched stays
        consistent and the next maintain scan converges without it."""
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=2.0)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            buffered = sum(len(v["rows"]) for v in self._pending.values())
        return {
            "dropped_batches": self.dropped_batches,
            "dropped_rows": self.dropped_rows,
            "gather_errors": self.gather_errors,
            "buffered_rows": buffered,
        }
