"""Hash-embedding table: the TPU-native EmbeddingVariable.

DeepRec's EmbeddingVariable (/root/reference/tensorflow/core/framework/embedding/
embedding_var.h:53) is a C++ resource wrapping a lockless hash map, a filter
policy and tiered storage; its hot loop is per-key pointer chasing
(kv_variable_lookup_ops.cc:255-306). That design cannot map onto XLA's
static-shape, functional world — so this is a redesign, not a port:

  * The table IS a pytree of dense arrays living in HBM: `keys [C]`,
    `values [C, D]`, `freq [C]`, `version [C]`, plus optimizer slot arrays.
    C is a fixed power-of-two capacity; growth is a host-orchestrated rehash
    into a larger table (recompiles once per capacity).
  * Lookup-or-create is a *vectorized* open-addressing probe: every pending id
    gathers its candidate slot, matches or claims empty slots via batched
    scatter, losers of a claim race advance to the next probe offset. The loop
    is a `lax.while_loop` of pure gathers/scatters — no per-key host loop,
    everything lands on the VPU.
  * Admission filters, frequency/version tracking and initialization are
    masked vector updates on the same arrays.
  * Eviction rebuilds the table (rare, checkpoint-time), which also heals
    probe chains — no tombstones on the hot path.

All ops are pure: they take a TableState and return a new one; XLA's buffer
donation makes the updates in-place in practice.
"""
from __future__ import annotations

import dataclasses
import functools as _ft
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as _np
from flax import struct

from deeprec_tpu.config import TableConfig
from deeprec_tpu.utils import hashing


def _key_dtype(cfg: TableConfig):
    return jnp.dtype(cfg.key_dtype)


@_ft.lru_cache(maxsize=1)
def _backend_is_tpu() -> bool:
    """Whether jax resolves to a TPU backend (cached — the backend cannot
    change within a process). The packed layout's "auto" gate."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def empty_key(cfg: TableConfig) -> int:
    """Reserved sentinel marking a free slot (min value of the key dtype)."""
    return int(jnp.iinfo(_key_dtype(cfg)).min)


# Row indices of the packed per-slot metadata leaf (TableState.meta, [3, C]):
# freq / version / dirty live in ONE int32 array so the train hot path
# updates all three with a single fused scatter instead of three. The layout
# is [3, C] (columns minor) — a [C, 3] layout would lane-pad 3 -> 128 on TPU
# and waste ~42x HBM; with C minor the array tiles like any other big row.
META_FREQ = 0
META_VERSION = 1
META_DIRTY = 2

# Per-column fill values for a fresh/vacated slot: freq 0, version -1
# (never touched), dirty 0.
_META_FILL = (0, -1, 0)


def empty_meta(capacity: int) -> jnp.ndarray:
    """[3, C] metadata array of an empty table."""
    return jnp.tile(
        jnp.asarray(_META_FILL, jnp.int32)[:, None], (1, capacity)
    )


# int8 residency quantization range: symmetric, -127..127 (the -128 code is
# unused so negation is exact and the scale maps max|row| onto the top code).
QMAX = 127.0


def quantize_rows_int8(rows: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row symmetric int8 quantization for the serving residency:
    returns (q, scale) with rows ≈ q * scale[:, None]. `q` is
    integer-valued float32 in [-127, 127] (scatter_rows_any casts to the
    table's int8 on the way in — exact for integer values), `scale` [U]
    float32 = max|row| / 127, 0 for all-zero rows (which decode to 0)."""
    rows = jnp.asarray(rows, jnp.float32)
    amax = jnp.max(jnp.abs(rows), axis=-1)
    scale = amax / QMAX
    inv = jnp.where(scale > 0, 1.0 / jnp.maximum(scale, 1e-30), 0.0)
    q = jnp.clip(jnp.round(rows * inv[..., None]), -QMAX, QMAX)
    return q, scale


@struct.dataclass
class TableState:
    """Device-resident state of one table (a pytree; donate it through jit).

    Scan-carry contract (Trainer.train_steps runs K steps in one
    `lax.scan`, threading every TableState through the carry): all leaves
    keep a FIXED shape and dtype across a step — lookups/applies/admission
    return arrays of the same aval, and the transient counters
    (insert_fails, a2a_overflow) accumulate as int32 scalars, never
    promote. Anything host-shaped (growth, eviction rebuilds to a new
    capacity, multi-tier sync) stays OUTSIDE the scan, at K-step
    boundaries — it changes leaf shapes, which a scan carry cannot."""

    keys: jnp.ndarray  # [C] key_dtype, empty slots hold the sentinel
    values: jnp.ndarray  # [C, D] value_dtype
    # [3, C] int32 — fused per-slot metadata, rows META_FREQ / META_VERSION /
    # META_DIRTY (lookup counter for admission + LFU tiering; global step of
    # last touch for TTL evict; touched-since-last-incremental-save flag).
    # One leaf so the train hot path reads and writes all three with a
    # single gather + a single scatter; the named `freq`/`version`/`dirty`
    # properties below keep every metadata READER (eviction, filters,
    # multi-tier, checkpoint, maintain) on the columnar view, and
    # `replace_meta` is the columnar WRITE entry point for cold paths.
    meta: jnp.ndarray
    slots: Dict[str, jnp.ndarray]  # optimizer slot arrays, [C, D] or [C, 1]
    bloom: Optional[jnp.ndarray]  # [M] int32 counting-Bloom sketch (CBF filter)
    insert_fails: jnp.ndarray  # [] int32 — ids that found no slot (grow signal)
    # [] int32 — ids past the all2all per-destination budget (the knob is
    # a2a_slack, NOT capacity — kept separate from insert_fails). Transient;
    # not checkpointed, resets on rebuild.
    a2a_overflow: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((), jnp.int32)
    )
    # Dedup-engine telemetry (ops/dedup.py), train lookups only. Same
    # transient contract as the counters above: int32 scalars accumulating
    # inside the K-step scan, not checkpointed, reset on rebuild and by
    # Trainer.update_budgets (which folds them into the auto-budget EMA).
    #   dedup_overflow — distinct ids compacted out past the unique budget
    #                    (served the blocked default that step)
    #   dedup_unique   — accumulated budgeted unique ids seen
    #   dedup_ids      — accumulated non-pad id positions those covered
    dedup_overflow: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((), jnp.int32)
    )
    dedup_unique: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((), jnp.int32)
    )
    dedup_ids: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((), jnp.int32)
    )
    # Owner-side exchange-load telemetry (sharded train lookups only —
    # ShardedTable.resolve; single-device tables never move these). Same
    # transient int32-scalar contract as the dedup counters; reset by
    # Trainer.update_budgets. Per mesh position (the leading shard axis of
    # a sharded TrainState), these expose the exchange imbalance the
    # placement plan (parallel/placement.py) flattens:
    #   owner_arrivals — exchanged rows this shard owned/served (a key
    #                    present on k source shards counts k)
    #   owner_unique   — distinct keys those arrivals deduped to
    owner_arrivals: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((), jnp.int32)
    )
    owner_unique: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((), jnp.int32)
    )
    # [C] float32 per-row dequantization scale — present ONLY on int8
    # serving-residency tables (cfg.value_dtype == "int8"): a stored row
    # decodes as values[i].astype(f32) * qscale[i]. None everywhere else
    # (None is an empty pytree node, so fp32/bf16 tables are structurally
    # unchanged). Written by the checkpoint import (quantize-on-import)
    # and read by the lookup gathers; rebuild relocates it like any other
    # per-row array.
    qscale: Optional[jnp.ndarray] = None

    @property
    def capacity(self) -> int:
        return self.keys.shape[0]

    @property
    def dim(self) -> int:
        """Logical embedding dim. Robust to the packed small-dim layout
        (values [C // P, P * D] — ops/packed.py): D * rows stays C * dim."""
        return self.values.shape[-1] * self.values.shape[-2] // self.keys.shape[-1]

    # Columnar views of the fused metadata leaf. Leading (table-group /
    # shard) axes pass through untouched — meta is [..., 3, C], the views
    # are [..., C], the same shapes the three separate leaves had.

    @property
    def freq(self) -> jnp.ndarray:
        return self.meta[..., META_FREQ, :]

    @property
    def version(self) -> jnp.ndarray:
        return self.meta[..., META_VERSION, :]

    @property
    def dirty(self) -> jnp.ndarray:
        return self.meta[..., META_DIRTY, :] != 0

    def replace_meta(self, freq=None, version=None, dirty=None) -> "TableState":
        """Columnar metadata write for cold paths (restore, tier sync,
        tests): rebuild the packed leaf from whole replacement columns.
        The hot path never comes here — it scatters fused [3]-rows."""
        meta = self.meta
        if freq is not None:
            meta = meta.at[..., META_FREQ, :].set(
                jnp.asarray(freq, jnp.int32))
        if version is not None:
            meta = meta.at[..., META_VERSION, :].set(
                jnp.asarray(version, jnp.int32))
        if dirty is not None:
            meta = meta.at[..., META_DIRTY, :].set(
                jnp.asarray(dirty, jnp.int32))
        return self.replace(meta=meta)


@struct.dataclass
class UniqueLookup:
    """Result of a deduplicated lookup, the unit the grad path works on."""

    uids: jnp.ndarray  # [U] unique ids (sentinel-padded)
    slot_ix: jnp.ndarray  # [U] int32 slot index, -1 when absent/blocked
    inverse: jnp.ndarray  # [N] position -> index into uids
    counts: jnp.ndarray  # [U] int32 occurrences in this batch
    valid: jnp.ndarray  # [U] bool — real id (not padding)
    admitted: jnp.ndarray  # [U] bool — passes the admission filter
    embeddings: jnp.ndarray  # [U, D] gathered values (default where blocked)
    # [U, D] forward RESIDUAL: the raw (unmasked, pre-admission) value rows
    # gathered at safe_ix during the lookup. `embeddings` is a masked view
    # of these rows; `apply_gradients` reuses them in place of its own
    # value re-gather (the rows cannot go stale between a train lookup and
    # its same-step apply — inserts only claim empty slots). Empty ([0])
    # signals "no residual carried" and the apply falls back to a gather.
    rows: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((0,), jnp.float32)
    )


class EmbeddingTable:
    """Pure-function API around TableState for one TableConfig.

    The public surface mirrors what tf.get_embedding_variable +
    tf.nn.embedding_lookup deliver in DeepRec (variable_scope.py:2146,
    embedding_ops.py:365), re-cut for functional SPMD training.
    """

    def __init__(self, cfg: TableConfig):
        self.cfg = cfg

    @property
    def quantized(self) -> bool:
        """int8 serving residency: rows store int8 + per-row fp32 scale
        (TableState.qscale) and every lookup gather dequantizes. Serving
        only — train-mode lookups raise (train fp32, serve quantized)."""
        return self.cfg.value_dtype == "int8"

    def _dequant(self, emb: jnp.ndarray, safe_ix: jnp.ndarray,
                 state: TableState) -> jnp.ndarray:
        """Decode gathered int8 rows: one [U] scale gather + a broadcast
        multiply — the whole dequantization cost of the serving path."""
        scale = state.qscale.at[safe_ix].get(mode="clip")
        return emb.astype(jnp.float32) * scale[:, None]

    @property
    def use_pallas(self) -> bool:
        """Fused Pallas kernels for the row gather/scatter hot path.
        "auto" resolves to pallas where the measured-winners flag says the
        bench crowned it: tools/bench_lookup.py on v5e measured the DMA
        kernels ahead wherever they're eligible (dim%128==0, f32 tables:
        gather 494 vs 362 GB/s, scatter 1117 vs 726 — docs/perf.md), and
        the ops self-gate back to XLA for ineligible shapes/backends, so
        "auto" is always the measured winner (AUTO_TRUSTS_F32_ROW flips
        it off if a re-bench ever disagrees)."""
        from deeprec_tpu.ops.fused_lookup import AUTO_TRUSTS_F32_ROW

        return self.cfg.kernel == "pallas" or (
            self.cfg.kernel == "auto" and AUTO_TRUSTS_F32_ROW
        )

    @property
    def pair_kernels(self) -> bool:
        """bf16 pair-granule kernels (gather + in-kernel-SR scatter): on
        for explicit kernel="pallas"; "auto" keeps XLA for bf16 until a
        hardware bench crowns the pair kernels (AUTO_TRUSTS_BF16_PAIR —
        the measured-winners policy)."""
        from deeprec_tpu.ops.fused_lookup import AUTO_TRUSTS_BF16_PAIR

        return self.cfg.kernel == "pallas" or (
            self.cfg.kernel == "auto" and AUTO_TRUSTS_BF16_PAIR
        )

    @property
    def fused_step(self) -> bool:
        """Single-pass fused step kernels (fused_sparse_forward /
        fused_sparse_backward: dedup-probe + gather + combine forward,
        segment-sum + optimizer + scatter backward): on for explicit
        kernel="pallas"; "auto" keeps the split-phase path until a
        hardware bench crowns them (AUTO_TRUSTS_FUSED_STEP — the same
        measured-winners policy as the pair kernels)."""
        from deeprec_tpu.ops.fused_lookup import AUTO_TRUSTS_FUSED_STEP

        return self.cfg.kernel == "pallas" or (
            self.cfg.kernel == "auto" and AUTO_TRUSTS_FUSED_STEP
        )

    def bag_forward(self, state: TableState, row_ix: jnp.ndarray, *,
                    combiner: str = "mean", unique_size: int,
                    interpret: bool = False):
        """Single-pass bag lookup over RESOLVED slot indices [B, L]
        (< 0 = pad): hash-probe dedup + unique-row gather + segment
        combine in one fused op (ops/fused_lookup.fused_sparse_forward),
        dispatched through the same kernel= gate as the row kernels.
        Returns a FusedBags; pair it with optim.apply.apply_bag_gradients
        for the fused backward. Packed small-dim layouts keep the
        split-phase lookup — the fused kernels address whole logical
        rows."""
        from deeprec_tpu.ops import fused_lookup as fl
        from deeprec_tpu.ops.packed import is_unpacked

        if not is_unpacked(state.values, state.capacity):
            raise NotImplementedError(
                "bag_forward: packed small-dim layouts keep the "
                "split-phase lookup (the fused step kernels address "
                "whole logical rows)"
            )
        return fl.fused_sparse_forward(
            state.values, row_ix, combiner=combiner,
            unique_size=unique_size, interpret=interpret,
            use_pallas=self.fused_step,
        )

    def pack_width(self, width: int, capacity: Optional[int] = None) -> int:
        """Pack factor for a [C, width] per-row array under this table's
        layout policy. cfg.packed="auto" packs only where the layout can
        win — TPU, where XLA pads the minor dim to 128 lanes; on CPU there
        is no padding and packing measured -34% (BENCH_r04 vs r03), so auto
        resolves to unpacked. "on"/"off" force it either way."""
        mode = self.cfg.packed
        if mode == "off" or (mode == "auto" and not _backend_is_tpu()):
            return 1
        from deeprec_tpu.ops.packed import pack_factor

        return pack_factor(width,
                           self.cfg.capacity if capacity is None else capacity)

    def pack(self, capacity: Optional[int] = None) -> int:
        """Pack factor for the values array at this capacity (ops/packed.py:
        P rows per 128-lane granule when dim < 128 divides 128). Packing is
        a storage-layout decision independent of the kernel choice — it
        saves P x HBM (XLA pads the minor dim to 128 lanes) and makes the
        table eligible for the fused DMA kernels at any kernel= setting.
        Gated per-backend by cfg.packed (see pack_width)."""
        return self.pack_width(self.cfg.dim, capacity)

    def _gather(self, values: jnp.ndarray, ix: jnp.ndarray,
                capacity: int) -> jnp.ndarray:
        """values[ix] with clip semantics through the configured kernel,
        packed-layout aware."""
        from deeprec_tpu.ops.packed import gather_rows_any

        return gather_rows_any(
            values, ix, capacity,
            use_pallas=self.use_pallas, pair_kernels=self.pair_kernels,
        )

    def _scatter(self, values: jnp.ndarray, slot_ix: jnp.ndarray,
                 rows: jnp.ndarray, capacity: int,
                 seed: jnp.ndarray | int = 0) -> jnp.ndarray:
        """Write rows at logical slot_ix (< 0 = skip) through the configured
        kernel, packed-layout aware; bf16 tables stochastic-round."""
        from deeprec_tpu.ops.packed import scatter_rows_any

        return scatter_rows_any(
            values, slot_ix, rows, capacity, seed,
            use_pallas=self.use_pallas, pair_kernels=self.pair_kernels,
        )

    # Hashable-by-config so EmbeddingTable can ride through jit as a static
    # argument (the jitted public methods below rely on this).
    def __hash__(self):
        return hash(self.cfg)

    def __eq__(self, other):
        return isinstance(other, EmbeddingTable) and self.cfg == other.cfg

    # ------------------------------------------------------------------ state

    def create(self) -> TableState:
        cfg = self.cfg
        C, D = cfg.capacity, cfg.dim
        kdt = _key_dtype(cfg)
        vdt = jnp.dtype(cfg.value_dtype)
        bloom = None
        if cfg.ev.cbf_filter is not None:
            bloom = jnp.zeros((cfg.ev.cbf_filter.num_cells(),), jnp.int32)
        P = self.pack()
        return TableState(
            keys=jnp.full((C,), empty_key(cfg), kdt),
            values=jnp.zeros((C // P, P * D), vdt),
            meta=empty_meta(C),
            slots={},
            bloom=bloom,
            insert_fails=jnp.zeros((), jnp.int32),
            qscale=(
                jnp.zeros((C,), jnp.float32) if self.quantized else None
            ),
        )

    # ------------------------------------------------------------- initializer

    def default_salt(self) -> int:
        return hashing.name_salt(self.cfg.name)

    def _init_rows(self, uids: jnp.ndarray, salt=None) -> jnp.ndarray:
        """Initializer values for newly created keys — a pure function of
        (key, table salt), so creation is reproducible anywhere (EV
        Initializer semantics, docs/docs_en/Embedding-Variable.md). Grouped
        tables pass a traced per-table salt through vmap."""
        cfg = self.cfg
        init = cfg.ev.init
        D = cfg.dim
        # Quantized tables serve missing-key defaults at full precision:
        # the initializer row never lives in the int8 residency, it is
        # computed fresh per lookup, so there is nothing to dequantize.
        vdt = jnp.float32 if self.quantized else jnp.dtype(cfg.value_dtype)
        if salt is None:
            salt = self.default_salt()
        if init.kind == "constant":
            return jnp.full((uids.shape[0], D), init.constant, vdt)
        if init.kind == "matrix_normal":
            # DeepRec: row (key % default_value_dim) of a fixed normal matrix.
            # The matrix itself is regenerated from the salt, not stored.
            dvd = init.default_value_dim
            rows = (uids.astype(jnp.uint32) % jnp.uint32(dvd)).astype(jnp.int32)
            u = hashing.stateless_uniform_from_ids(
                rows[:, None] * jnp.int32(D)
                + jax.lax.broadcasted_iota(jnp.int32, (1, D), 1),
                salt=jnp.asarray(salt).astype(jnp.uint32) ^ jnp.uint32(0x5EED),
            )
            return self._uniform_to_normal(u).astype(vdt)
        # stateless_normal: per-key deterministic normal from the id hash.
        u = hashing.stateless_uniform_from_ids(
            uids[:, None] * jnp.int32(max(D, 1))
            + jax.lax.broadcasted_iota(jnp.int32, (1, D), 1),
            salt=salt,
        )
        return self._uniform_to_normal(u).astype(vdt)

    def _uniform_to_normal(self, u: jnp.ndarray) -> jnp.ndarray:
        init = self.cfg.ev.init
        # inverse-CDF approximation via erfinv: N(mean, stddev)
        eps = 1e-6
        z = jnp.sqrt(2.0) * jax.scipy.special.erfinv(
            jnp.clip(2.0 * u - 1.0, -1.0 + eps, 1.0 - eps)
        )
        return init.mean + init.stddev * z

    # ------------------------------------------------------------ probe/insert

    def _probe(
        self,
        keys: jnp.ndarray,
        uids: jnp.ndarray,
        want_create: jnp.ndarray,
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Vectorized open-addressing lookup-or-create.

        Args:
          keys: [C] current key array.
          uids: [U] unique ids to resolve.
          want_create: [U] bool — ids allowed to claim an empty slot.

        Returns: (new_keys, slot_ix [U] (-1 = not found/placed), created [U],
        failed [U]).
        """
        cfg = self.cfg
        C = keys.shape[0]
        mask_c = jnp.uint32(C - 1)
        h = hashing.mix32(hashing.fold64(uids))
        sentinel = jnp.asarray(empty_key(cfg), keys.dtype)
        valid = uids != sentinel

        slot_ix0 = jnp.full(uids.shape, -1, jnp.int32)
        created0 = jnp.zeros(uids.shape, bool)
        pending0 = valid

        def cond(carry):
            step, pending, *_ = carry
            return jnp.logical_and(step < cfg.max_probes, jnp.any(pending))

        def body(carry):
            step, pending, slot_ix, created, keys = carry
            pos = ((h + jnp.uint32(step)) & mask_c).astype(jnp.int32)  # [U]
            k = keys[pos]
            found = pending & (k == uids)
            slot_ix = jnp.where(found, pos, slot_ix)
            pending = pending & ~found
            is_empty = k == sentinel
            want = pending & is_empty & want_create
            # Claim race: scatter all claimants; duplicates resolve to one
            # winner, which the re-gather below reveals. Losers keep probing.
            claim_pos = jnp.where(want, pos, C)  # C = out of bounds -> dropped
            keys = keys.at[claim_pos].set(uids, mode="drop")
            won = want & (keys[pos] == uids)
            slot_ix = jnp.where(won, pos, slot_ix)
            created = created | won
            pending = pending & ~won
            # ids at a *non*-creatable empty slot stop probing: the key is
            # definitively absent (linear probing invariant).
            give_up = pending & is_empty & ~want_create
            pending = pending & ~give_up
            return step + 1, pending, slot_ix, created, keys

        step, pending, slot_ix, created, keys = jax.lax.while_loop(
            cond, body, (jnp.int32(0), pending0, slot_ix0, created0, keys)
        )
        failed = pending  # ran out of probes: table (region) is full
        return keys, slot_ix, created, failed

    # ----------------------------------------------------------------- lookup

    def default_unique_size(self, n: int) -> Optional[int]:
        """Resolve cfg.unique_budget for an n-position flattened TRAIN
        lookup: the uids-array size for the hash dedup engine, or None for
        the legacy U = N sort-unique (logged once per table so the waste
        is visible — None/"auto" configs; "off" stays silent). Trainers
        override this resolution with their own (EMA-driven) budgets.
        Eval/serving lookups never budget by default: resident keys must
        read exactly, and read-only state makes overflow invisible to the
        counters (callers may still force a size explicitly)."""
        from deeprec_tpu.ops import dedup

        ub = self.cfg.unique_budget
        if isinstance(ub, int) and not isinstance(ub, bool):
            return dedup.resolve_size(ub, n)
        if ub != "off":  # None or "auto": visible fallback
            dedup.log_full_fallback(self.cfg.name, n)
        return None

    def lookup_unique(
        self,
        state: TableState,
        ids: jnp.ndarray,
        *,
        step: jnp.ndarray | int = 0,
        train: bool = True,
        pad_value: int = -1,
        unique_size: Optional[int] = None,
    ) -> Tuple[TableState, UniqueLookup]:
        if unique_size is None and train:
            unique_size = self.default_unique_size(
                int(_np.prod(ids.shape)) if ids.ndim else 1
            )
        return _lookup_unique_jit(
            self, state, ids, jnp.asarray(step, jnp.int32), train, pad_value,
            unique_size,
        )

    def _route_ids(
        self, ids: jnp.ndarray, pad_value: int,
        unique_size: Optional[int],
    ):
        """Routing half of a lookup (ops/dedup.py `route_ids`): flatten +
        pad-collapse + dedup. Pure function of the id batch — no table
        state — so pipelined trainers hoist it a full step ahead."""
        from deeprec_tpu.ops import dedup

        return dedup.route_ids(
            ids, pad_value=pad_value, sentinel=empty_key(self.cfg),
            unique_size=unique_size,
        )

    def _resolve_routed(
        self,
        state: TableState,
        route,
        *,
        step,
        train: bool,
        salt=None,
    ) -> Tuple[TableState, UniqueLookup]:
        """Key/metadata half on a prepared route: probe/insert, metadata
        stamp, init-scatter for created rows, admission, dedup telemetry —
        everything EXCEPT the value-row gather (`_finish_resolved`). The
        returned result carries placeholder (0-sized) embeddings/rows;
        `rows.size == 0` is the documented "not gathered yet" sentinel.

        Hoist contract (the basis of the exact pipelined scan): nothing
        here reads or writes the VALUE rows an apply touches — keys/meta
        are apply-invariant on the diet hot path (stamp_meta=False), and
        the init scatter only lands on slots that were empty at claim
        time, which a concurrent apply (whose rows were all resident at
        its own lookup) cannot overlap. So resolve(t+1) commutes with
        apply(t) bit-exactly.
        """
        uids, inverse, counts, valid, overflow = route
        state, res = self._resolve(
            state, uids, counts, valid, step=step, train=train, salt=salt
        )
        if train:
            # Seed the auto-budget EMA (Trainer.update_budgets) on every
            # path; the overflow counter only moves under a budget.
            state = state.replace(
                dedup_unique=state.dedup_unique
                + jnp.sum(valid).astype(jnp.int32),
                dedup_ids=state.dedup_ids + jnp.sum(counts),
                dedup_overflow=(
                    state.dedup_overflow + overflow
                    if overflow is not None
                    else state.dedup_overflow
                ),
            )
        return state, dataclasses.replace(res, inverse=inverse)

    def _lookup_unique_impl(
        self,
        state: TableState,
        ids: jnp.ndarray,
        step,
        train: bool,
        pad_value: int,
        unique_size: Optional[int],
        salt=None,
    ) -> Tuple[TableState, UniqueLookup]:
        """Deduplicate ids, resolve/insert them, gather embeddings.

        `ids` may be any shape; padding positions equal to `pad_value` are
        ignored (standard for ragged sparse features). In train mode new keys
        are inserted, frequencies incremented and versions stamped — the
        combined semantics of KvResourceGather + the freq/version bookkeeping
        DeepRec does inside EmbeddingVar::GetEmbeddings/LookupOrCreateKey.

        Dedup routing: `unique_size=None` keeps the legacy sort-based
        `jnp.unique` at U = N; a concrete `unique_size` engages the O(N)
        hash dedup engine (ops/dedup.py) at that static budget — every
        downstream op then runs at U instead of N, ids past the budget
        serve the blocked default and count into `dedup_overflow`.

        Split-phase composition: route (`_route_ids`) → resolve
        (`_resolve_routed`) → finish (`_finish_resolved`) — the pipelined
        trainers call the three phases individually so the value gather
        can land after the previous step's apply while everything else
        hoists ahead of it.
        """
        route = self._route_ids(ids, pad_value, unique_size)
        state, res = self._resolve_routed(
            state, route, step=step, train=train, salt=salt
        )
        return state, self._finish_resolved(state, res)

    def _lookup_resolved(
        self,
        state: TableState,
        uids: jnp.ndarray,
        counts: jnp.ndarray,
        valid: jnp.ndarray,
        *,
        step: jnp.ndarray | int,
        train: bool,
        salt=None,
    ) -> Tuple[TableState, UniqueLookup]:
        """Core lookup on already-unique ids (also the per-shard entry point
        for sharded tables, where dedup happened before the all-to-all):
        resolve (probe/insert/meta/init/admission) + finish (value gather)."""
        state, res = self._resolve(
            state, uids, counts, valid, step=step, train=train, salt=salt
        )
        return state, self._finish_resolved(state, res)

    def _resolve(
        self,
        state: TableState,
        uids: jnp.ndarray,
        counts: jnp.ndarray,
        valid: jnp.ndarray,
        *,
        step: jnp.ndarray | int,
        train: bool,
        salt=None,
    ) -> Tuple[TableState, UniqueLookup]:
        """Key/metadata half of `_lookup_resolved`: probe-or-insert keys,
        fused metadata stamp, initializer scatter for created rows and the
        admission decision — but NOT the value-row gather, which
        `_finish_resolved` performs (the split the pipelined trainers use
        to place the gather after the previous step's apply). Returns the
        updated state and a UniqueLookup whose embeddings/rows are 0-sized
        placeholders."""
        cfg = self.cfg
        if train and self.quantized:
            raise ValueError(
                f"table {cfg.name}: int8 residency is serving-only — train "
                "fp32 and restore into a quantized Predictor "
                "(Predictor(quantize='int8'))"
            )
        step = jnp.asarray(step, jnp.int32)

        bloom = state.bloom
        want_create = valid
        if not train:
            want_create = jnp.zeros_like(valid)
        elif cfg.ev.cbf_filter is not None:
            # CBF admission: bump the sketch, only keys at/above threshold may
            # occupy a table slot (bloom_filter_policy.h semantics).
            from deeprec_tpu.embedding import filters as _filters

            bloom, est = _filters.cbf_add(cfg.ev.cbf_filter, bloom, uids, counts)
            want_create = valid & (est >= cfg.ev.cbf_filter.filter_freq)

        keys, slot_ix, created, failed = self._probe(state.keys, uids, want_create)

        present = slot_ix >= 0
        safe_ix = jnp.where(present, slot_ix, 0)

        need_filter = (
            cfg.ev.counter_filter is not None
            and cfg.ev.counter_filter.filter_freq > 0
        )
        values = state.values
        meta = state.meta
        f_cur = None  # post-update per-uid frequency (admission input)
        if train:
            # Initialize newly created rows (bf16 tables stochastic-round
            # the initializer, same as every later write).
            init_rows = self._init_rows(uids, salt)
            values = self._scatter(
                values, jnp.where(created, slot_ix, -1), init_rows,
                state.capacity, seed=step,
            )
            # Fused metadata update: ONE [3, U] gather + ONE [3, U]
            # scatter replace the former freq add / version set / dirty
            # set trio. The gather also feeds the admission filter, whose
            # legacy post-update freq read it subsumes (uids are unique,
            # so each present id owns its slot and set == read-add-write).
            upd_ix = jnp.where(present, slot_ix, state.capacity)
            m_rows = meta.at[:, safe_ix].get(mode="clip")  # [3, U]
            f_cur = m_rows[META_FREQ] + counts
            new_rows = jnp.stack([
                f_cur,
                jnp.broadcast_to(step, f_cur.shape).astype(jnp.int32),
                jnp.ones_like(f_cur),
            ])
            meta = meta.at[:, upd_ix].set(new_rows, mode="drop")
        elif need_filter:
            f_cur = meta[META_FREQ].at[safe_ix].get(mode="clip")

        # Admission: counter filter gates on the (just updated) frequency.
        admitted = present
        if need_filter:
            admitted = present & (f_cur >= cfg.ev.counter_filter.filter_freq)

        new_state = state.replace(
            keys=keys,
            values=values,
            meta=meta,
            bloom=bloom,
            insert_fails=state.insert_fails + jnp.sum(failed).astype(jnp.int32),
        )
        res = UniqueLookup(
            uids=uids,
            slot_ix=slot_ix,
            inverse=jnp.zeros((0,), jnp.int32),  # filled by lookup_unique
            counts=counts,
            valid=valid,
            admitted=admitted,
            # Placeholders until _finish_resolved gathers the value rows.
            embeddings=jnp.zeros((0, 0), jnp.float32),
            rows=jnp.zeros((0, 0), jnp.float32),
        )
        return new_state, res

    def _finish_resolved(
        self, state: TableState, res: UniqueLookup, keep_rows: bool = True
    ) -> UniqueLookup:
        """Value half of a lookup: gather the resolved rows from
        `state.values` and apply the admission mask. Reads the CURRENT
        values — in the pipelined scan this runs after the previous step's
        apply, which is exactly what keeps the lookahead staleness-free.
        `keep_rows=False` drops the raw-row residual (callers that will
        never reuse it — the stale-by-one apply — avoid carrying a second
        [U, D] buffer across dispatches); `rows.size == 0` stays the
        documented "no residual, re-gather at apply" sentinel."""
        safe_ix = jnp.where(res.slot_ix >= 0, res.slot_ix, 0)
        emb = self._gather(state.values, safe_ix, state.capacity)
        if self.quantized:
            emb = self._dequant(emb, safe_ix, state)
        blocked_default = jnp.asarray(
            self.cfg.ev.init.default_value_no_permission, emb.dtype
        )
        masked = jnp.where(res.admitted[:, None], emb, blocked_default)
        rows = emb if keep_rows else jnp.zeros((0, 0), jnp.float32)
        return dataclasses.replace(res, embeddings=masked, rows=rows)

    def lookup_readonly(
        self, state: TableState, ids: jnp.ndarray, pad_value: int = -1,
        salt: Optional[int] = None,
    ) -> jnp.ndarray:
        """Serving lookup. For grouped/stacked tables pass the per-feature
        salt used at training time so missing keys serve the same
        initializer vector training would have created."""
        return _lookup_readonly_jit(self, state, ids, pad_value, salt)

    def _lookup_readonly_impl(
        self, state: TableState, ids: jnp.ndarray, pad_value: int = -1,
        salt=None,
    ) -> jnp.ndarray:
        """Serving-path lookup: no insertion, no counter updates. Missing keys
        serve their initializer value (what a fresh key would have trained
        from), padding serves zeros."""
        cfg = self.cfg
        shape = ids.shape
        flat = ids.reshape(-1)
        sentinel = jnp.asarray(empty_key(cfg), flat.dtype)
        is_pad = flat == jnp.asarray(pad_value, flat.dtype)
        flat = jnp.where(is_pad, sentinel, flat)
        keys, slot_ix, _, _ = self._probe(
            state.keys, flat, jnp.zeros(flat.shape, bool)
        )
        del keys  # unchanged: no creation
        present = slot_ix >= 0
        safe_ix = jnp.where(present, slot_ix, 0)
        emb = self._gather(state.values, safe_ix, state.capacity)
        if self.quantized:
            emb = self._dequant(emb, safe_ix, state)
        emb = jnp.where(present[:, None], emb, self._init_rows(flat, salt))
        emb = jnp.where(is_pad[:, None], 0.0, emb)
        return emb.reshape(*shape, cfg.dim)

    # ---------------------------------------------------------------- updates

    def scatter_update(
        self,
        state: TableState,
        slot_ix: jnp.ndarray,
        new_values: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        seed: jnp.ndarray | int = 0,
    ) -> TableState:
        """Write rows back (optimizers use this through their own slot logic).
        Pass the global step as `seed` when the table is bf16 so stochastic
        rounding draws fresh bits each step."""
        ok = slot_ix >= 0
        if mask is not None:
            ok = ok & mask
        values = self._scatter(
            state.values, jnp.where(ok, slot_ix, -1), new_values,
            state.capacity, seed=seed,
        )
        ix = jnp.where(ok, slot_ix, state.capacity)
        # Standalone writes (no same-step train lookup stamped these rows)
        # keep their own dirty marking so incremental saves see them.
        meta = state.meta.at[META_DIRTY, ix].set(1, mode="drop")
        return state.replace(values=values, meta=meta)

    # ------------------------------------------------------- evict & rebuild

    def occupied(self, state: TableState) -> jnp.ndarray:
        return state.keys != jnp.asarray(empty_key(self.cfg), state.keys.dtype)

    def size(self, state: TableState) -> jnp.ndarray:
        """Live key count — EV's Size()/tf.EVGetSize analog."""
        return jnp.sum(self.occupied(state)).astype(jnp.int32)

    def evict_mask(self, state: TableState, step: jnp.ndarray | int) -> jnp.ndarray:
        """Which occupied slots the eviction policies would drop
        (docs/docs_en/Feature-Eviction.md: GlobalStepEvict + L2WeightEvict)."""
        cfg = self.cfg
        occ = self.occupied(state)
        drop = jnp.zeros_like(occ)
        gse = cfg.ev.global_step_evict
        if gse is not None and gse.steps_to_live > 0:
            drop = drop | (
                jnp.asarray(step, jnp.int32) - state.version > gse.steps_to_live
            )
        l2e = cfg.ev.l2_weight_evict
        if l2e is not None and l2e.l2_weight_threshold >= 0:
            from deeprec_tpu.ops.packed import unpack_array

            norm2 = jnp.sum(
                unpack_array(state.values, state.capacity).astype(jnp.float32)
                ** 2,
                axis=1,
            )
            drop = drop | (norm2 < l2e.l2_weight_threshold)
        return occ & drop

    def rebuild(
        self, state: TableState, keep: Optional[jnp.ndarray] = None,
        new_capacity: Optional[int] = None,
        slot_fills: Optional[Tuple[Tuple[str, float], ...]] = None,
    ) -> TableState:
        """Re-insert surviving entries into a fresh table.

        Used for (a) eviction — linear probing cannot delete in place without
        breaking chains, and rebuilds also re-compact them — and (b) growth to
        a larger capacity. O(C), runs at checkpoint cadence, fully on device.
        """
        cfg = self.cfg
        C_new = new_capacity or state.capacity
        if C_new & (C_new - 1):
            raise ValueError("new_capacity must be a power of two")
        occ = self.occupied(state)
        if keep is not None:
            occ = occ & keep
        sentinel = jnp.asarray(empty_key(cfg), state.keys.dtype)
        uids = jnp.where(occ, state.keys, sentinel)

        fresh_keys = jnp.full((C_new,), sentinel, state.keys.dtype)
        fresh_keys, slot_ix, created, failed = self._probe(fresh_keys, uids, occ)
        # Survivors always fit: C_new >= live count and probing is unbounded
        # only by max_probes — extremely unlikely to fail at <=50% load, but
        # surface it if it happens.
        ix = jnp.where(slot_ix >= 0, slot_ix, C_new)

        from deeprec_tpu.ops.packed import pack_array, unpack_array

        def move(arr, fill):
            out = jnp.full((C_new,) + arr.shape[1:], fill, arr.dtype)
            return out.at[ix].set(arr, mode="drop")

        def move_rows(arr, fill):
            """Per-row 2-D arrays relocate in LOGICAL layout, then repack
            at the new capacity's factor (growth can change eligibility —
            rebuild runs at checkpoint cadence, the relayout is fine).
            pack_width applies the cfg.packed backend gate."""
            logical = unpack_array(arr, state.capacity)
            moved = move(logical, fill)
            return pack_array(moved, self.pack_width(logical.shape[1], C_new))

        from deeprec_tpu.optim.sparse import SCALAR_PREFIX

        # Relocate the fused metadata in one scatter; vacated slots take the
        # per-column fills (freq 0 / version -1 / dirty 0).
        meta = empty_meta(C_new).at[:, ix].set(state.meta, mode="drop")

        return TableState(
            keys=fresh_keys,
            values=move_rows(state.values, 0),
            meta=meta,
            slots={
                # Per-table scalar slots (e.g. AdamAsync beta powers, shape
                # [1, 1]) are not per-key rows — pass them through. Freed
                # per-key rows reset to the optimizer's slot INIT value
                # (slot_fills), not 0 — an Adagrad accumulator reborn at 0
                # would rsqrt(0) into NaN on a zero-grad dim.
                k: (
                    v
                    if k.startswith(SCALAR_PREFIX)
                    else move_rows(v, dict(slot_fills or ()).get(k, 0))
                )
                for k, v in state.slots.items()
            },
            bloom=state.bloom,
            insert_fails=jnp.sum(failed).astype(jnp.int32),
            qscale=(
                None if state.qscale is None else move(state.qscale, 0.0)
            ),
        )

    def evict(self, state: TableState, step: jnp.ndarray | int,
              slot_fills: Optional[Tuple[Tuple[str, float], ...]] = None
              ) -> TableState:
        return _evict_jit(self, state, jnp.asarray(step, jnp.int32), slot_fills)

    def grow(self, state: TableState, new_capacity: int,
             slot_fills: Optional[Tuple[Tuple[str, float], ...]] = None
             ) -> TableState:
        """Host-orchestrated growth (recompiles downstream jits once per
        capacity — the price of dynamic tables in a static-shape world).
        Pass the optimizer's slot_fills so rows later created in the new
        empty slots start from the slot INIT value, not 0."""
        return self.rebuild(state, new_capacity=new_capacity,
                            slot_fills=slot_fills)


# --------------------------------------------------------------------------
# Jitted trampolines: public methods route through these so eager callers
# (tests, serving glue) hit the compile cache instead of op-by-op dispatch.
# Inside a user jit they inline into the surrounding program.

import functools as _functools


@_functools.partial(jax.jit, static_argnums=(0, 4, 5, 6))
def _lookup_unique_jit(table, state, ids, step, train, pad_value, unique_size):
    return table._lookup_unique_impl(state, ids, step, train, pad_value, unique_size)


@_functools.partial(jax.jit, static_argnums=(0, 3, 4))
def _lookup_readonly_jit(table, state, ids, pad_value, salt):
    return table._lookup_readonly_impl(state, ids, pad_value, salt)


@_functools.partial(jax.jit, static_argnums=0)
def probe_jit(table, keys, uids, want_create):
    """Jitted lookup-or-create probe for restore/replay paths: the eager
    while_loop dispatches op-by-op and dominated delta-replay latency
    (poll_updates under serving load). Compile-cached per (table,
    shapes) — pair with power-of-two row bucketing (import_rows)."""
    return table._probe(keys, uids, want_create)


@_functools.partial(jax.jit, static_argnums=(0, 3))
def _evict_jit(table, state, step, slot_fills):
    drop = table.evict_mask(state, step)
    return table.rebuild(state, keep=~drop, slot_fills=slot_fills)
