"""Segment combiners for ragged sparse features.

DeepRec combines per-sample bags of embeddings with sum/mean/sqrtn inside
embedding_lookup_sparse (/root/reference/tensorflow/python/ops/
embedding_ops.py:484) and its fused kernels. On TPU the ragged bag is a dense
[B, L] padded id matrix; the combine is a masked reduction the compiler fuses
straight into the downstream matmul.
"""
from __future__ import annotations

import jax.numpy as jnp


def combine(
    emb_u: jnp.ndarray,  # [U, D] unique embeddings
    inverse: jnp.ndarray,  # [B, L] position -> unique index
    mask: jnp.ndarray,  # [B, L] bool, True for real (non-pad) ids
    combiner: str = "mean",
) -> jnp.ndarray:
    """Gather per-position embeddings from the unique set and reduce each bag.

    Differentiable w.r.t. emb_u: the backward pass is exactly the
    scatter-of-gradients DeepRec's _GatherGrad + sparse-apply pipeline
    produces (kv_variable_ops.py:1092), computed by autodiff.
    """
    e = emb_u[inverse]  # [B, L, D]
    m = mask[..., None].astype(e.dtype)
    s = jnp.sum(e * m, axis=1)  # [B, D]
    n = jnp.sum(m, axis=1)  # [B, 1]
    if combiner == "sum":
        return s
    if combiner == "mean":
        return s / jnp.maximum(n, 1.0)
    if combiner == "sqrtn":
        return s / jnp.sqrt(jnp.maximum(n, 1.0))
    raise ValueError(f"unknown combiner: {combiner}")
