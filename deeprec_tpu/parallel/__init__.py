from deeprec_tpu.parallel.mesh import (
    DATA_AXIS,
    INTER_AXIS,
    INTRA_AXIS,
    make_mesh,
    make_mesh_2d,
    mesh_batch_axes,
    shard_batch,
)
from deeprec_tpu.parallel.sharded import ShardedLookup, ShardedRoute, ShardedTable
from deeprec_tpu.parallel.trainer import ShardedTrainer
from deeprec_tpu.parallel.async_stage import AsyncShardedTrainer, AsyncState
from deeprec_tpu.parallel.ring_attention import ring_attention, ring_attention_sharded
