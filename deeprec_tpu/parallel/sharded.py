"""Pod-sharded embedding tables: lookup/apply inside shard_map.

This is the subsystem that dissolves DeepRec's distributed parameter plane —
the async-PS graph partitioning, the seastar/GRPC++ data plane
(contrib/star/*), StarServer's lock-free PS runtime and SOK's embedding
all2all (addons/sparse_operation_kit) — into compiled XLA collectives over
ICI (SURVEY.md §2.5, §3.5).

Design (per table, inside one `shard_map` region spanning the train step):

  forward:
    local ids --unique--> local uniques U
    all_gather(uids)                 # tiny: G = N*U int32
    owner mask = hash_shard(id) == my_shard
    owner-side global dedup + lookup_or_create on the LOCAL shard state
    embeddings scattered back to gathered layout, zero elsewhere
    psum_scatter over the shard axis  ->  [U, D] local unique embeddings
  backward:
    all_gather(grad_u)               # [G, D]
    segment-sum into owner-unique rows (cross-replica duplicate ids merge
    here — this is what makes the update exact synchronous SGD, unlike the
    racy lock-free applies of StarServer)
    one fused sparse-apply on the local shard

Every collective is a single XLA op riding ICI; there is no parameter-server
process, no RPC stack, no send/recv graph partitioning.

Split-phase lookup (the in-step pipelining substrate, docs/perf.md round
11): the forward decomposes into `route` (local dedup + the ID exchange +
owner-side dedup — a pure function of the id batch), `resolve` (owner probe/
insert, metadata, init — reads keys/meta, never value rows) and `finish`
(value gather + the embedding exchange). The pipelined K-step scan hoists
route+resolve of batch t+1 ahead of batch t's dense compute and places
finish after batch t's apply, which hides the id exchange and the probe
bookkeeping behind the matmuls with zero staleness. `exchange_chunks > 1`
additionally splits the value/grad exchanges into column chunks — several
smaller collectives XLA's async scheduler can pipeline against the
surrounding gather/segment-sum compute (`pipeline_mode="chunked"`).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from deeprec_tpu.training.profiler import phase_scope

from deeprec_tpu.embedding.table import EmbeddingTable, TableState, UniqueLookup, empty_key
from deeprec_tpu.optim import apply as optim_apply
from deeprec_tpu.optim.sparse import SparseOptimizer
from deeprec_tpu.parallel.mesh import DATA_AXIS, AxisSpec


@struct.dataclass
class ShardedRoute:
    """Apply-independent routing half of a sharded lookup (lives inside
    shard_map): local dedup, the id exchange and the owner-side dedup. A
    pure function of the id batch — it reads NO table state — so the
    pipelined scan hoists it (and the id collective it contains) a full
    step ahead of the tables it will hit."""

    inverse: jnp.ndarray  # [B, L] position -> local unique index
    counts: jnp.ndarray  # [U] local unique counts
    valid: jnp.ndarray  # [U]
    o_uids: jnp.ndarray  # [O] owner-side unique ids this shard received
    o_inverse: jnp.ndarray  # [G] exchanged-position -> owner-unique index
    o_counts: jnp.ndarray  # [O]
    o_valid: jnp.ndarray  # [O]
    owned: jnp.ndarray  # [G] bool — valid rows this shard received/owns
    # Local-dedup overflow (None on the legacy sort path).
    loc_overflow: Optional[jnp.ndarray]
    # a2a path only: [U] position of each local unique id in the [N*Bd]
    # send buffer (-1 = overflow, served default this step) and the scalar
    # overflow count; empty/None for allgather. The hier path reuses
    # send_slot for the RELAY's inter-tier slots ([Rr], -1 = overflow)
    # and a2a_overflow for the relay overflow count.
    send_slot: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((0,), jnp.int32)
    )
    a2a_overflow: Optional[jnp.ndarray] = None
    # hier path only: per gathered intra-tier position [R = I*U] — whether
    # THIS device is the relay for that position's id, and the position's
    # index into the relay-unique rows. Empty for flat comms.
    h_rel_mask: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((0,), bool)
    )
    h_r_inverse: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((0,), jnp.int32)
    )


@struct.dataclass
class ShardedLookup:
    """Per-device result of a sharded lookup (lives inside shard_map).

    `resolve` returns it with 0-sized placeholder `embeddings` (the value
    half not yet gathered/exchanged); `finish` fills them. Only finished
    results reach the model / the apply."""

    inverse: jnp.ndarray  # [B, L] position -> local unique index
    counts: jnp.ndarray  # [U] local unique counts
    valid: jnp.ndarray  # [U]
    embeddings: jnp.ndarray  # [U, D] local unique embeddings
    owner_res: UniqueLookup  # owner-side lookup (slot ids on the local shard)
    o_inverse: jnp.ndarray  # [G] exchanged-position -> owner-unique index
    owned: jnp.ndarray  # [G] bool — valid rows this shard received/owns
    # a2a path only: [U] position of each local unique id in the [N*Bd] send
    # buffer (-1 = overflow, served default this step); empty for allgather.
    # hier: the RELAY's inter-tier slots ([Rr], -1 = overflow).
    send_slot: jnp.ndarray = struct.field(default_factory=lambda: jnp.zeros((0,), jnp.int32))
    # hier path only (see ShardedRoute): relay mask + relay-unique inverse
    # over the gathered intra-tier layout [R = I*U]; empty for flat comms.
    h_rel_mask: jnp.ndarray = struct.field(default_factory=lambda: jnp.zeros((0,), bool))
    h_r_inverse: jnp.ndarray = struct.field(default_factory=lambda: jnp.zeros((0,), jnp.int32))


class ShardedTable:
    """Collective lookup/apply for one table sharded over `axis` (call the
    methods from inside a shard_map over that axis; state is the LOCAL shard's
    TableState with capacity = global_capacity / num_shards).

    Three exchange strategies:
      * comm="allgather" (default): all_gather ids + psum_scatter embeddings.
        Exact for any skew; comm volume ~ U·D·(N−1) per device.
      * comm="a2a": budgeted id all2all → owner lookup → embedding all2all —
        the SOK lookup_sparse design (SURVEY.md §3.5). Comm volume
        ~ slack·U·D, an ~N/2× reduction. Ids are bucketed by owner with a
        per-destination budget of slack·U/N; overflow beyond the budget
        (astronomically unlikely under a uniform hash at slack=2) serves the
        default value for that step and is counted in state.a2a_overflow —
        the knob for it is a2a_slack, NOT capacity (insert_fails is the
        separate capacity/grow signal).
      * comm="hier": the two-tier exchange of a `make_mesh_2d` mesh
        (docs/multihost.md). Ids are gathered on the cheap `intra` tier,
        cross-device duplicates collapse at a per-group RELAY (device i of
        each group aggregates the group's ids whose owner sits at intra
        position i), and only the aggregated per-group uniques cross the
        expensive `inter` tier in a budgeted all2all (ops/traffic.py
        `hier_dest_budgets` — the PR-15 per-dest discipline applied at the
        group tier). Values and grads retrace both tiers in reverse with
        fp32 accumulation at the relay and the owner; both wires ride
        `exchange_dtype`. Inter-tier overflow serves the default value and
        counts in state.a2a_overflow, same as "a2a". Requires `axis` to be
        the (inter, intra) name tuple plus the `intra`/`inter` sizes.

    On a 2-D mesh the FLAT comms still work unchanged: pass the (inter,
    intra) axis tuple and every collective enumerates devices in flat
    host-major rank order, bit-identical to the 1-D mesh program.

    `exchange_chunks > 1` splits the value/grad payload exchanges into that
    many column chunks — bitwise-identical arithmetic (per-element reduction
    order unchanged; chunks write disjoint columns), but several smaller
    collectives whose wire time XLA can overlap with the neighbouring
    gather/segment-sum compute (software pipelining; the
    `pipeline_mode="chunked"` knob threads through here). The id exchange
    stays whole — it is already tiny.
    """

    def __init__(
        self,
        table: EmbeddingTable,
        num_shards: int,
        axis: AxisSpec = DATA_AXIS,
        comm: str = "allgather",
        a2a_slack: float = 2.0,
        exchange_chunks: int = 1,
        intra: Optional[int] = None,
        inter: Optional[int] = None,
        hier_group_factor: Optional[float] = None,
    ):
        self.table = table
        self.num_shards = num_shards
        self.axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        self.comm = comm
        self.a2a_slack = a2a_slack
        self.exchange_chunks = max(1, int(exchange_chunks))
        # Two-tier geometry (comm="hier"): `axis` must be the (inter,
        # intra) tuple of a make_mesh_2d mesh; `hier_group_factor` is the
        # static per-group unique budget U_g = factor·U (None = exact
        # intra·U — group overlap can never overflow the inter bucket).
        self.intra = int(intra) if intra is not None else None
        self.inter = int(inter) if inter is not None else None
        self.hier_group_factor = hier_group_factor
        if comm == "hier":
            if not (isinstance(self.axis, tuple) and len(self.axis) == 2):
                raise ValueError(
                    "comm='hier' needs axis=(inter, intra) name tuple, "
                    f"got {self.axis!r}"
                )
            if not self.intra or not self.inter:
                raise ValueError("comm='hier' needs intra/inter sizes")
            if self.intra * self.inter != num_shards:
                raise ValueError(
                    f"hier mesh {self.inter}x{self.intra} != "
                    f"num_shards {num_shards}"
                )
        # Plan-aware per-destination a2a budget inputs (see _a2a_budget):
        # `plan_dest_hot` is the active plan's per-destination explicit
        # hot-key arrival counts ([N] ints; None = uniform hash) and
        # `plan_hot_count` how many plan hot keys leave the hash-spread
        # tail. Both are static trace-time constants set by
        # ShardedTrainer.update_placement at plan adoption (before the
        # jit rebuild).
        self.plan_dest_hot = None
        self.plan_hot_count = 0
        # Trace-time record of the budget the compiled program actually
        # uses — the measured side of the measured==modeled budget assert
        # (bench.py drift arm, tests/test_placement_v2.py).
        self.last_a2a_unique = None
        self.last_a2a_budgets = None
        self.last_a2a_bucket = None

    # --------------------------------------------------------- split phases

    def route(
        self,
        ids: jnp.ndarray,
        *,
        pad_value: int = -1,
        unique_size: Optional[int] = None,
        plan=None,
    ) -> ShardedRoute:
        """Routing phase: local dedup (`unique_size` engages the hash
        engine at that static budget), the id exchange, and the owner-side
        dedup. Depends only on `ids` — no table state — so it can be
        issued arbitrarily early.

        `plan` is an optional placement-plan leaf dict
        (parallel/placement.py): owner-offset rotation + hot-key routing
        table consulted before `hash_shard`, so zipf head keys spread
        across the mesh instead of hammering their hash-home. None/{}
        keeps the uniform hash (identical program)."""
        if self.comm == "a2a":
            return self._route_a2a(ids, pad_value, unique_size, plan)
        if self.comm == "hier":
            return self._route_hier(ids, pad_value, unique_size, plan)
        return self._route_allgather(ids, pad_value, unique_size, plan)

    def resolve(
        self,
        state: TableState,
        route: ShardedRoute,
        *,
        step: jnp.ndarray | int = 0,
        train: bool = True,
        salt=None,
    ) -> Tuple[TableState, ShardedLookup]:
        """Owner-side key/metadata phase on a prepared route: probe/insert
        on the local shard, fused metadata stamp, init scatter for created
        rows, admission, and the dedup/a2a telemetry counters. Touches
        keys/meta/new rows only — never the value rows an apply writes —
        so resolve(t+1) commutes bit-exactly with apply(t) (the hoist
        contract of the pipelined scan). Returns a pending ShardedLookup
        whose embeddings await `finish`."""
        state, res = self.table._resolve(
            state, route.o_uids, route.o_counts, route.o_valid, step=step,
            train=train, salt=salt,
        )
        state = self._count_dedup(
            state, route.counts, route.valid, route.loc_overflow, train
        )
        if train:
            # Owner-side load telemetry: how many exchanged rows THIS
            # shard owns this step (arrivals — a hot key present on k
            # source shards counts k) and how many distinct keys those
            # dedup to. The per-mesh-position imbalance of these counters
            # is what the placement plan flattens (dedup_stats per_shard,
            # bench.py --placement).
            state = state.replace(
                owner_arrivals=state.owner_arrivals
                + jnp.sum(route.owned).astype(jnp.int32),
                owner_unique=state.owner_unique
                + jnp.sum(route.o_valid).astype(jnp.int32),
            )
        if train and route.a2a_overflow is not None:
            state = state.replace(
                a2a_overflow=state.a2a_overflow + route.a2a_overflow
            )
        return state, ShardedLookup(
            inverse=route.inverse,
            counts=route.counts,
            valid=route.valid,
            embeddings=jnp.zeros((0, 0), jnp.float32),
            owner_res=res,
            o_inverse=route.o_inverse,
            owned=route.owned,
            send_slot=route.send_slot,
            h_rel_mask=route.h_rel_mask,
            h_r_inverse=route.h_r_inverse,
        )

    def finish(
        self,
        state: TableState,
        sl: ShardedLookup,
        *,
        train: bool = True,
        keep_rows: bool = True,
    ) -> ShardedLookup:
        """Value phase: gather the resolved owner rows from the CURRENT
        values array and run the embedding exchange (chunked when
        `exchange_chunks > 1`). In the pipelined scan this runs after the
        previous step's apply — which is exactly what keeps the lookahead
        staleness-free. `keep_rows=False` drops the owner-side residual
        for callers that never reuse it (the stale-by-one apply)."""
        o_res = self.table._finish_resolved(
            state, sl.owner_res, keep_rows=keep_rows
        )
        if self.comm == "a2a":
            return self._finish_a2a(sl, o_res, train)
        if self.comm == "hier":
            return self._finish_hier(sl, o_res, train)
        return self._finish_allgather(sl, o_res, train)

    def lookup_unique(
        self,
        state: TableState,
        ids: jnp.ndarray,
        *,
        step: jnp.ndarray | int = 0,
        train: bool = True,
        pad_value: int = -1,
        salt=None,
        unique_size: Optional[int] = None,
        plan=None,
    ) -> Tuple[TableState, ShardedLookup]:
        """`unique_size` (static) engages the hash dedup engine at that
        budget BEFORE the exchange: the all_gather/all2all id payload, the
        owner-side work and the embedding return all shrink by the same
        U/N factor. None keeps the legacy sort-unique at U = N.

        Composition of the split phases — route → resolve → finish; the
        pipelined trainers call the phases individually."""
        route = self.route(
            ids, pad_value=pad_value, unique_size=unique_size, plan=plan
        )
        state, sl = self.resolve(
            state, route, step=step, train=train, salt=salt
        )
        return state, self.finish(state, sl, train=train)

    # ------------------------------------------------------- shared helpers

    def _wire_dtype(self, train: bool):
        """Dtype of the value/grad payloads on the wire. TRAIN exchanges
        ride cfg.exchange_dtype (default bf16 — halves ICI bytes both ways;
        the owner side always segment-sums in fp32, and in the forward each
        gathered position has exactly ONE nonzero contributor, so even the
        psum_scatter reduction is exact at the wire precision). Eval and
        serving exchanges stay exact fp32 regardless."""
        if train and self.table.cfg.exchange_dtype == "bfloat16":
            return jnp.bfloat16
        return jnp.float32

    def _col_chunks(self, D: int):
        """Static [start, stop) column blocks of the value/grad exchange —
        `exchange_chunks` near-equal pieces (each >= 1 column)."""
        k = max(1, min(self.exchange_chunks, int(D)))
        bounds = [round(i * D / k) for i in range(k + 1)]
        return [(a, b) for a, b in zip(bounds, bounds[1:]) if b > a]

    def _owner_dedup(self, g_ids, g_counts, include, sentinel,
                     budgeted: bool = False):
        """Dedup exchanged ids on the owner side (the same id may arrive from
        many peers) and segment-sum their counts. Under a budget the dedup
        is the sort-free hash engine sized to hold every exchanged id (a
        few pad slots over G), so the owner side never overflows."""
        G = g_ids.shape[0]
        if budgeted:
            from deeprec_tpu.ops import dedup

            o_uids, o_inverse, o_counts, _ = dedup.hash_dedup(
                jnp.where(include, g_ids, sentinel),
                dedup.resolve_size(G, G),
                sentinel=empty_key(self.table.cfg),
                weights=jnp.where(include, g_counts, 0),
            )
            return o_uids, o_inverse, o_counts, o_uids != sentinel
        o_uids, o_inverse, _ = jnp.unique(
            jnp.where(include, g_ids, sentinel), size=G, fill_value=sentinel,
            return_inverse=True, return_counts=True,
        )
        o_valid = o_uids != sentinel
        o_counts = (
            jnp.zeros((G,), jnp.int32)
            .at[o_inverse]
            .add(jnp.where(include, g_counts, 0))
        )
        return o_uids, o_inverse, jnp.where(o_valid, o_counts, 0), o_valid

    def _count_dedup(self, state, counts, valid, overflow, train):
        """Accumulate the dedup telemetry counters on the local shard's
        state (mirrors EmbeddingTable._lookup_unique_impl)."""
        if not train:
            return state
        return state.replace(
            dedup_unique=state.dedup_unique + jnp.sum(valid).astype(jnp.int32),
            dedup_ids=state.dedup_ids + jnp.sum(counts),
            dedup_overflow=(
                state.dedup_overflow + overflow
                if overflow is not None
                else state.dedup_overflow
            ),
        )

    # -------------------------------------------------------- allgather path

    def _route_allgather(self, ids, pad_value, unique_size,
                         plan=None) -> ShardedRoute:
        from deeprec_tpu.ops import dedup
        from deeprec_tpu.parallel import placement

        N = self.num_shards
        axis = self.axis
        sent_py = empty_key(self.table.cfg)
        uids, inverse, counts, valid, loc_ovf = dedup.route_ids(
            ids, pad_value=pad_value, sentinel=sent_py,
            unique_size=unique_size,
        )
        sentinel = jnp.asarray(sent_py, uids.dtype)

        # Exchange unique ids (cheap: ints) so every shard sees all
        # candidates — under a budget the gathered G = N·U shrinks with U.
        g_uids = jax.lax.all_gather(uids, axis, tiled=True)  # [G]
        g_counts = jax.lax.all_gather(counts, axis, tiled=True)  # [G]
        me = jax.lax.axis_index(axis)
        owned = (placement.plan_owner(g_uids, N, plan) == me) & (
            g_uids != sentinel
        )
        o_uids, o_inverse, o_counts, o_valid = self._owner_dedup(
            g_uids, g_counts, owned, sentinel, budgeted=unique_size is not None
        )
        return ShardedRoute(
            inverse=inverse, counts=counts, valid=valid,
            o_uids=o_uids, o_inverse=o_inverse, o_counts=o_counts,
            o_valid=o_valid, owned=owned, loc_overflow=loc_ovf,
        )

    def _finish_allgather(self, sl: ShardedLookup, o_res: UniqueLookup,
                          train: bool) -> ShardedLookup:
        # Back to gathered layout; non-owned rows contribute zero, then one
        # reduce-scatter hands each replica its own unique rows. The value
        # payload rides the wire dtype (train: bf16 by default) — exact as a
        # reduction because each row has one nonzero contributor.
        wire = self._wire_dtype(train)
        e_g = o_res.embeddings[sl.o_inverse] * sl.owned[:, None].astype(
            o_res.embeddings.dtype
        )
        parts = []
        for ci, (a, b) in enumerate(self._col_chunks(e_g.shape[1])):
            with phase_scope(f"exchange_chunk{ci}"):
                parts.append(jax.lax.psum_scatter(
                    e_g[:, a:b].astype(wire), self.axis,
                    scatter_dimension=0, tiled=True,
                ))
        emb_local = (
            parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        ).astype(jnp.float32)  # [U, D]
        return sl.replace(embeddings=emb_local, owner_res=o_res)

    # ------------------------------------------------------------- a2a path

    def _a2a_budget(self, U: int) -> int:
        from deeprec_tpu.ops import traffic as T

        # Per-destination budget vector (ops/traffic.py a2a_dest_budgets):
        # destination d pays the hash-spread TAIL share — slack·(U−H)/N,
        # H = the plan's hot-key count, keys the routing table sends
        # explicitly and so never compete for tail slots — plus exactly
        # the hot-key arrivals the plan routes to d (every source that
        # sees a hot key sends it to the same planned owner, so the
        # per-(source, dest) concentration is the plan's own bincount).
        # The compiled bucket is the vector's max: all_to_all moves equal
        # chunks, so an SPMD program cannot ship ragged per-destination
        # buckets — but the max is still strictly tighter than the v1
        # global-headroom bucket (full tail + the worst concentration on
        # EVERY bucket) once the plan routes enough hot keys. Uniform
        # hash (no plan) reproduces the legacy slack·U/N budget
        # bit-for-bit. The inputs are static trace-time constants
        # (update_placement sets them before the jit rebuild); genuine
        # shortfall still degrades via the sentinel bucket — default
        # served, counted in a2a_overflow — never dropped rows.
        budgets = T.a2a_dest_budgets(
            unique=U, num_shards=self.num_shards, slack=self.a2a_slack,
            dest_hot=self.plan_dest_hot, hot_count=self.plan_hot_count,
        )
        self.last_a2a_unique = int(U)  # noqa: DRT002 — static trace-time shape, no device value
        self.last_a2a_budgets = budgets
        self.last_a2a_bucket = int(budgets.max())  # noqa: DRT002 — max of a host numpy budget vector, no device value
        return self.last_a2a_bucket

    def _route_a2a(self, ids, pad_value, unique_size,
                   plan=None) -> ShardedRoute:
        from deeprec_tpu.ops import dedup
        from deeprec_tpu.parallel import placement

        N = self.num_shards
        axis = self.axis
        sent_py = empty_key(self.table.cfg)
        uids, inverse, counts, valid, loc_ovf = dedup.route_ids(
            ids, pad_value=pad_value, sentinel=sent_py,
            unique_size=unique_size,
        )
        sentinel = jnp.asarray(sent_py, uids.dtype)
        # Under a budget U shrinks, so the per-destination bucket Bd and
        # both all2all payloads shrink by the same factor.
        U = uids.shape[0]

        # Bucket by owner with a per-destination budget.
        Bd = self._a2a_budget(U)
        owner = jnp.where(
            valid, placement.plan_owner(uids, N, plan), jnp.int32(N)
        )  # invalid sort last
        sort_ix = jnp.argsort(owner, stable=True)
        sorted_owner = owner[sort_ix]
        start = jnp.searchsorted(sorted_owner, jnp.arange(N, dtype=owner.dtype))
        rank = jnp.arange(U, dtype=jnp.int32) - start[
            jnp.clip(sorted_owner, 0, N - 1)
        ].astype(jnp.int32)
        slot_sorted = jnp.where(
            (sorted_owner < N) & (rank < Bd), sorted_owner * Bd + rank, -1
        )
        send_slot = jnp.zeros((U,), jnp.int32).at[sort_ix].set(slot_sorted)
        overflow = (send_slot < 0) & valid
        sslot_safe = jnp.where(send_slot >= 0, send_slot, N * Bd)

        buf_ids = jnp.full((N * Bd,), sentinel, uids.dtype).at[sslot_safe].set(
            uids, mode="drop"
        )
        buf_counts = jnp.zeros((N * Bd,), jnp.int32).at[sslot_safe].set(
            counts, mode="drop"
        )
        # Exchange: row j of the receive buffer = the bucket peer j sent us.
        recv_ids = jax.lax.all_to_all(
            buf_ids.reshape(N, Bd), axis, split_axis=0, concat_axis=0, tiled=True
        ).reshape(-1)
        recv_counts = jax.lax.all_to_all(
            buf_counts.reshape(N, Bd), axis, split_axis=0, concat_axis=0,
            tiled=True,
        ).reshape(-1)

        recv_valid = recv_ids != sentinel
        o_uids, o_inverse, o_counts, o_valid = self._owner_dedup(
            recv_ids, recv_counts, recv_valid, sentinel,
            budgeted=unique_size is not None,
        )
        return ShardedRoute(
            inverse=inverse, counts=counts, valid=valid,
            o_uids=o_uids, o_inverse=o_inverse, o_counts=o_counts,
            o_valid=o_valid, owned=recv_valid, loc_overflow=loc_ovf,
            send_slot=send_slot,
            a2a_overflow=jnp.sum(overflow).astype(jnp.int32),
        )

    def _finish_a2a(self, sl: ShardedLookup, o_res: UniqueLookup,
                    train: bool) -> ShardedLookup:
        cfg = self.table.cfg
        N = self.num_shards
        G2 = sl.o_inverse.shape[0]
        Bd = G2 // N
        # Embedding return payload in the wire dtype (train: bf16 default).
        wire = self._wire_dtype(train)
        e_out = o_res.embeddings[sl.o_inverse].astype(wire)
        e_out = e_out * sl.owned[:, None].astype(wire)
        parts = []
        for ci, (a, b) in enumerate(self._col_chunks(e_out.shape[1])):
            with phase_scope(f"exchange_chunk{ci}"):
                parts.append(jax.lax.all_to_all(
                    e_out[:, a:b].reshape(N, Bd, b - a), self.axis,
                    split_axis=0, concat_axis=0, tiled=True,
                ).reshape(G2, b - a))
        e_back = (
            parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        ).astype(jnp.float32)
        # e_back[send_slot[u]] is u's embedding; overflow/invalid -> default.
        emb_local = e_back.at[jnp.where(sl.send_slot >= 0, sl.send_slot, 0)].get(
            mode="clip"
        )
        blocked = jnp.asarray(
            cfg.ev.init.default_value_no_permission, jnp.float32
        )
        emb_local = jnp.where((sl.send_slot >= 0)[:, None], emb_local, blocked)
        return sl.replace(embeddings=emb_local, owner_res=o_res)

    def _apply_a2a(
        self, state, opt, sl, grad_u, *, step, lr, grad_averaging,
        reuse_rows, stamp_meta,
    ) -> TableState:
        N = self.num_shards
        G2 = sl.o_inverse.shape[0]
        Bd = G2 // N
        D = grad_u.shape[1]
        wire = self._wire_dtype(True)  # the backward only exists in train
        sslot_safe = jnp.where(sl.send_slot >= 0, sl.send_slot, G2)
        # Segment-sum into owner-unique rows AT THE OWNER SIZE (== G2 on
        # the legacy path; a few pad slots over it under a budget). The
        # accumulation runs in fp32 on the owner side regardless of the
        # wire dtype. Chunked: each column block rides its own all_to_all
        # and lands in its own (disjoint) o_grad columns — bitwise the
        # same result, but the wire time of chunk k overlaps the
        # segment-sum of chunk k-1.
        O = sl.owner_res.uids.shape[0]
        parts = []
        for ci, (a, b) in enumerate(self._col_chunks(D)):
            g_buf = (
                jnp.zeros((G2, b - a), wire)
                .at[sslot_safe]
                .set(grad_u[:, a:b].astype(wire), mode="drop")
            )
            with phase_scope(f"exchange_chunk{ci}"):
                g_recv = jax.lax.all_to_all(
                    g_buf.reshape(N, Bd, b - a), self.axis, split_axis=0,
                    concat_axis=0, tiled=True,
                ).reshape(G2, b - a)
            parts.append(
                jnp.zeros((O, b - a), jnp.float32)
                .at[sl.o_inverse]
                .add(g_recv.astype(jnp.float32)
                     * sl.owned[:, None].astype(jnp.float32))
            )
        o_grad = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        # Same local-mean-loss rescale as the allgather path.
        o_grad = o_grad / jnp.float32(N)
        return optim_apply.apply_gradients(
            self.table, state, opt, sl.owner_res, o_grad, step=step, lr=lr,
            grad_averaging=grad_averaging, reuse_rows=reuse_rows,
            stamp_meta=stamp_meta,
        )

    # ------------------------------------------------- hierarchical path
    #
    # Two-tier exchange over a make_mesh_2d mesh (docs/multihost.md).
    # Forward ids: local dedup (U) → intra-tier allgather ([I·U], cheap
    # wire) → per-group relay dedup (device i of the group aggregates the
    # gathered ids whose owner sits at intra position i — the group's
    # uniques partition across relays, so each id crosses the expensive
    # tier exactly once per source group) → budgeted inter-tier a2a by
    # owner GROUP → owner dedup → resolve. The bucket a relay addresses
    # to owner group j lands on device (j, i) — exactly the owner,
    # because relay position i IS the owner's intra position. Values and
    # grads retrace the tiers in reverse: owner → inter a2a → relay →
    # intra psum_scatter/allgather, fp32 accumulation at relay and
    # owner, `exchange_dtype` on both wires (the forward stays exact at
    # bf16: every psum_scatter position has ONE nonzero contributor and
    # bf16∘bf16 rounding is idempotent; the backward's relay pre-sum
    # regroups the fp32 reduction, an ulp-level reordering — same class
    # as a2a-vs-allgather).

    def _hier_budget(self, U: int) -> int:
        from deeprec_tpu.ops import traffic as T

        # Per-destination-GROUP budget vector (ops/traffic.py
        # hier_dest_budgets): the PR-15 per-dest discipline applied at
        # the group tier — each relay holds ~U_g/I group uniques and
        # buckets them over J owner groups; the plan's per-device hot
        # arrivals fold to per-group maxima. Model and program share one
        # formula by construction; bench.py --mesh records the bucket
        # the trace used next to the modeled vector.
        budgets = T.hier_dest_budgets(
            unique=U, intra=self.intra, inter=self.inter,
            slack=self.a2a_slack, group_factor=self.hier_group_factor,
            dest_hot=self.plan_dest_hot, hot_count=self.plan_hot_count,
        )
        self.last_a2a_unique = int(U)  # noqa: DRT002 — static trace-time shape, no device value
        self.last_a2a_budgets = budgets
        self.last_a2a_bucket = int(budgets.max())  # noqa: DRT002 — max of a host numpy budget vector, no device value
        return self.last_a2a_bucket

    def _route_hier(self, ids, pad_value, unique_size,
                    plan=None) -> ShardedRoute:
        from deeprec_tpu.ops import dedup
        from deeprec_tpu.parallel import placement

        N = self.num_shards
        I, J = self.intra, self.inter
        ea, ia = self.axis  # (inter, intra) — mesh-major
        sent_py = empty_key(self.table.cfg)
        uids, inverse, counts, valid, loc_ovf = dedup.route_ids(
            ids, pad_value=pad_value, sentinel=sent_py,
            unique_size=unique_size,
        )
        sentinel = jnp.asarray(sent_py, uids.dtype)
        U = uids.shape[0]

        # --- intra tier: id/count gather inside the host group.
        with phase_scope("hier_intra_ids"):
            g_uids = jax.lax.all_gather(uids, ia, tiled=True)  # [I*U]
            g_counts = jax.lax.all_gather(counts, ia, tiled=True)
        owner = placement.plan_owner(g_uids, N, plan)  # [I*U]
        g_valid = g_uids != sentinel
        i_me = jax.lax.axis_index(ia)
        # Relay selection: flat rank r = g·I + i, so owner % I is the
        # owner's intra position — the coordinate the inter a2a cannot
        # change. Exactly one device per group relays a given position.
        rel_mask = ((owner % jnp.int32(I)) == i_me) & g_valid
        r_uids, r_inverse, r_counts, r_valid = self._owner_dedup(
            g_uids, g_counts, rel_mask, sentinel, budgeted=True
        )
        Rr = r_uids.shape[0]

        # --- inter tier: bucket relay uniques by owner group under the
        # per-group budget; overflow degrades to the sentinel bucket
        # (default-served, counted), never dropped rows.
        Bg = self._hier_budget(U)
        group = jnp.where(
            r_valid,
            placement.plan_owner(r_uids, N, plan) // jnp.int32(I),
            jnp.int32(J),
        )  # invalid sort last
        sort_ix = jnp.argsort(group, stable=True)
        sorted_group = group[sort_ix]
        start = jnp.searchsorted(
            sorted_group, jnp.arange(J, dtype=group.dtype)
        )
        rank = jnp.arange(Rr, dtype=jnp.int32) - start[
            jnp.clip(sorted_group, 0, J - 1)
        ].astype(jnp.int32)
        slot_sorted = jnp.where(
            (sorted_group < J) & (rank < Bg), sorted_group * Bg + rank, -1
        )
        send_slot = jnp.zeros((Rr,), jnp.int32).at[sort_ix].set(slot_sorted)
        overflow = (send_slot < 0) & r_valid
        sslot_safe = jnp.where(send_slot >= 0, send_slot, J * Bg)

        buf_ids = jnp.full((J * Bg,), sentinel, uids.dtype).at[
            sslot_safe
        ].set(r_uids, mode="drop")
        buf_counts = jnp.zeros((J * Bg,), jnp.int32).at[sslot_safe].set(
            r_counts, mode="drop"
        )
        with phase_scope("hier_inter_ids"):
            recv_ids = jax.lax.all_to_all(
                buf_ids.reshape(J, Bg), ea, split_axis=0, concat_axis=0,
                tiled=True,
            ).reshape(-1)
            recv_counts = jax.lax.all_to_all(
                buf_counts.reshape(J, Bg), ea, split_axis=0, concat_axis=0,
                tiled=True,
            ).reshape(-1)

        # Everything that arrives is owned by me (relay position == my
        # intra position, bucket == my group).
        recv_valid = recv_ids != sentinel
        o_uids, o_inverse, o_counts, o_valid = self._owner_dedup(
            recv_ids, recv_counts, recv_valid, sentinel, budgeted=True
        )
        return ShardedRoute(
            inverse=inverse, counts=counts, valid=valid,
            o_uids=o_uids, o_inverse=o_inverse, o_counts=o_counts,
            o_valid=o_valid, owned=recv_valid, loc_overflow=loc_ovf,
            send_slot=send_slot,
            a2a_overflow=jnp.sum(overflow).astype(jnp.int32),
            h_rel_mask=rel_mask, h_r_inverse=r_inverse,
        )

    def _finish_hier(self, sl: ShardedLookup, o_res: UniqueLookup,
                     train: bool) -> ShardedLookup:
        cfg = self.table.cfg
        J = self.inter
        ea, ia = self.axis
        G2 = sl.o_inverse.shape[0]  # J*Bg
        Bg = G2 // J
        wire = self._wire_dtype(train)
        # --- inter tier back: owner rows → relay buckets.
        e_out = o_res.embeddings[sl.o_inverse].astype(wire)
        e_out = e_out * sl.owned[:, None].astype(wire)
        D = e_out.shape[1]
        blocked = jnp.asarray(
            cfg.ev.init.default_value_no_permission, jnp.float32
        )
        parts = []
        for ci, (a, b) in enumerate(self._col_chunks(D)):
            with phase_scope(f"hier_inter_chunk{ci}"):
                e_back = jax.lax.all_to_all(
                    e_out[:, a:b].reshape(J, Bg, b - a), ea,
                    split_axis=0, concat_axis=0, tiled=True,
                ).reshape(G2, b - a).astype(jnp.float32)
            # e_back[send_slot[u]] is relay-unique u's row; inter-tier
            # overflow serves the default (the a2a degrade contract).
            v_r = e_back.at[
                jnp.where(sl.send_slot >= 0, sl.send_slot, 0)
            ].get(mode="clip")
            v_r = jnp.where((sl.send_slot >= 0)[:, None], v_r, blocked)
            # --- intra tier back: relay rows → gathered layout → one
            # reduce-scatter hands each device its own uniques. Exact at
            # the wire dtype: exactly one relay contributes per position.
            e_g = v_r[sl.h_r_inverse] * sl.h_rel_mask[:, None].astype(
                jnp.float32
            )
            with phase_scope(f"hier_intra_chunk{ci}"):
                parts.append(jax.lax.psum_scatter(
                    e_g.astype(wire), ia, scatter_dimension=0, tiled=True,
                ))
        emb_local = (
            parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        ).astype(jnp.float32)  # [U, D]
        return sl.replace(embeddings=emb_local, owner_res=o_res)

    def _apply_hier(
        self, state, opt, sl, grad_u, *, step, lr, grad_averaging,
        reuse_rows, stamp_meta,
    ) -> TableState:
        J = self.inter
        ea, ia = self.axis
        G2 = sl.o_inverse.shape[0]
        Bg = G2 // J
        Rr = sl.send_slot.shape[0]
        D = grad_u.shape[1]
        wire = self._wire_dtype(True)  # the backward only exists in train
        O = sl.owner_res.uids.shape[0]
        sslot_safe = jnp.where(sl.send_slot >= 0, sl.send_slot, G2)
        rel = sl.h_rel_mask[:, None].astype(jnp.float32)
        parts = []
        for ci, (a, b) in enumerate(self._col_chunks(D)):
            # Intra tier: grads gather inside the group at the wire
            # dtype; the relay segment-sums its positions in fp32 (the
            # cross-device duplicate merge happens HERE, before the
            # expensive tier — the byte diet of the whole design).
            with phase_scope(f"hier_intra_chunk{ci}"):
                g_g = jax.lax.all_gather(
                    grad_u[:, a:b].astype(wire), ia, tiled=True
                )  # [I*U, b-a]
            r_grad = (
                jnp.zeros((Rr, b - a), jnp.float32)
                .at[sl.h_r_inverse]
                .add(g_g.astype(jnp.float32) * rel)
            )
            # Inter tier: relay subtotals ride the budgeted buckets back
            # to the owner (overflowed rows drop, matching their
            # default-served forward); owner accumulates in fp32.
            g_buf = (
                jnp.zeros((G2, b - a), wire)
                .at[sslot_safe]
                .set(r_grad.astype(wire), mode="drop")
            )
            with phase_scope(f"hier_inter_chunk{ci}"):
                g_recv = jax.lax.all_to_all(
                    g_buf.reshape(J, Bg, b - a), ea, split_axis=0,
                    concat_axis=0, tiled=True,
                ).reshape(G2, b - a)
            parts.append(
                jnp.zeros((O, b - a), jnp.float32)
                .at[sl.o_inverse]
                .add(g_recv.astype(jnp.float32)
                     * sl.owned[:, None].astype(jnp.float32))
            )
        o_grad = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        # Same local-mean-loss rescale as the flat paths.
        o_grad = o_grad / jnp.float32(self.num_shards)
        return optim_apply.apply_gradients(
            self.table, state, opt, sl.owner_res, o_grad, step=step, lr=lr,
            grad_averaging=grad_averaging, reuse_rows=reuse_rows,
            stamp_meta=stamp_meta,
        )

    # ------------------------------------------------------------- backward

    def apply_gradients(
        self,
        state: TableState,
        opt: SparseOptimizer,
        sl: ShardedLookup,
        grad_u: jnp.ndarray,  # [U, D] grads w.r.t. sl.embeddings
        *,
        step: jnp.ndarray | int = 0,
        lr=None,
        grad_averaging: bool = False,
        reuse_rows: bool = False,
        stamp_meta: bool = True,
    ) -> TableState:
        """reuse_rows/stamp_meta thread to optim_apply.apply_gradients
        (safe legacy defaults; see its docstring). The sharded trainer's
        sync hot path opts into the diet — the owner-side residual
        (sl.owner_res.rows) replaces the apply's value gather — while the
        async stale-by-one apply keeps the defaults."""
        if self.comm == "a2a":
            return self._apply_a2a(
                state, opt, sl, grad_u, step=step, lr=lr,
                grad_averaging=grad_averaging, reuse_rows=reuse_rows,
                stamp_meta=stamp_meta,
            )
        if self.comm == "hier":
            return self._apply_hier(
                state, opt, sl, grad_u, step=step, lr=lr,
                grad_averaging=grad_averaging, reuse_rows=reuse_rows,
                stamp_meta=stamp_meta,
            )
        wire = self._wire_dtype(True)  # the backward only exists in train
        D = grad_u.shape[1]
        # Owner-unique rows: size == G legacy, G + pad under a budget.
        # Accumulate in fp32 whatever the wire dtype was. Chunked: one
        # all_gather + segment-sum per column block (disjoint o_grad
        # columns — bitwise identical, wire/computation pipelined).
        O = sl.owner_res.uids.shape[0]
        parts = []
        for ci, (a, b) in enumerate(self._col_chunks(D)):
            with phase_scope(f"exchange_chunk{ci}"):
                g_g = jax.lax.all_gather(
                    grad_u[:, a:b].astype(wire), self.axis, tiled=True
                )  # [G, b-a] — G = N·U shrinks with the unique budget
            parts.append(
                jnp.zeros((O, b - a), jnp.float32)
                .at[sl.o_inverse]
                .add(g_g.astype(jnp.float32)
                     * sl.owned[:, None].astype(jnp.float32))
            )
        o_grad = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        # Per-replica losses are means over the LOCAL batch (B/N); summing N
        # replicas' grads here would make the sparse step N x the
        # single-device one while dense grads get pmean'd. Rescale so both
        # paths see the global-batch-mean gradient.
        o_grad = o_grad / jnp.float32(self.num_shards)
        return optim_apply.apply_gradients(
            self.table,
            state,
            opt,
            sl.owner_res,
            o_grad,
            step=step,
            lr=lr,
            grad_averaging=grad_averaging,
            reuse_rows=reuse_rows,
            stamp_meta=stamp_meta,
        )
