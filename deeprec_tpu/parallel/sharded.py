"""Pod-sharded embedding tables: lookup/apply inside shard_map.

This is the subsystem that dissolves DeepRec's distributed parameter plane —
the async-PS graph partitioning, the seastar/GRPC++ data plane
(contrib/star/*), StarServer's lock-free PS runtime and SOK's embedding
all2all (addons/sparse_operation_kit) — into compiled XLA collectives over
ICI (SURVEY.md §2.5, §3.5).

Design (per table, inside one `shard_map` region spanning the train step):

  forward:
    local ids --unique--> local uniques U
    all_gather(uids)                 # tiny: G = N*U int32
    owner mask = hash_shard(id) == my_shard
    owner-side global dedup + lookup_or_create on the LOCAL shard state
    embeddings scattered back to gathered layout, zero elsewhere
    psum_scatter over the shard axis  ->  [U, D] local unique embeddings
  backward:
    all_gather(grad_u)               # [G, D]
    segment-sum into owner-unique rows (cross-replica duplicate ids merge
    here — this is what makes the update exact synchronous SGD, unlike the
    racy lock-free applies of StarServer)
    one fused sparse-apply on the local shard

Every collective is a single XLA op riding ICI; there is no parameter-server
process, no RPC stack, no send/recv graph partitioning.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from deeprec_tpu.embedding.table import EmbeddingTable, TableState, UniqueLookup, empty_key
from deeprec_tpu.optim import apply as optim_apply
from deeprec_tpu.optim.sparse import SparseOptimizer
from deeprec_tpu.utils import hashing


@struct.dataclass
class ShardedLookup:
    """Per-device result of a sharded lookup (lives inside shard_map)."""

    inverse: jnp.ndarray  # [B, L] position -> local unique index
    counts: jnp.ndarray  # [U] local unique counts
    valid: jnp.ndarray  # [U]
    embeddings: jnp.ndarray  # [U, D] local unique embeddings
    owner_res: UniqueLookup  # owner-side lookup (slot ids on the local shard)
    o_inverse: jnp.ndarray  # [G] gathered-position -> owner-unique index
    owned: jnp.ndarray  # [G] bool — rows this shard owns


class ShardedTable:
    """Collective lookup/apply for one table sharded over `axis` (call the
    methods from inside a shard_map over that axis; state is the LOCAL shard's
    TableState with capacity = global_capacity / num_shards)."""

    def __init__(self, table: EmbeddingTable, num_shards: int, axis: str = "data"):
        self.table = table
        self.num_shards = num_shards
        self.axis = axis

    def lookup_unique(
        self,
        state: TableState,
        ids: jnp.ndarray,
        *,
        step: jnp.ndarray | int = 0,
        train: bool = True,
        pad_value: int = -1,
        salt=None,
    ) -> Tuple[TableState, ShardedLookup]:
        cfg = self.table.cfg
        N = self.num_shards
        axis = self.axis
        sentinel = jnp.asarray(empty_key(cfg), ids.dtype)

        flat = ids.reshape(-1)
        U = flat.shape[0]
        flat = jnp.where(flat == jnp.asarray(pad_value, flat.dtype), sentinel, flat)
        uids, inverse, counts = jnp.unique(
            flat, size=U, fill_value=sentinel, return_inverse=True, return_counts=True
        )
        valid = uids != sentinel
        counts = jnp.where(valid, counts, 0).astype(jnp.int32)

        # Exchange unique ids (cheap: ints) so every shard sees all candidates.
        g_uids = jax.lax.all_gather(uids, axis, tiled=True)  # [G]
        g_counts = jax.lax.all_gather(counts, axis, tiled=True)  # [G]
        G = g_uids.shape[0]
        me = jax.lax.axis_index(axis)
        owned = (hashing.hash_shard(g_uids, N) == me) & (g_uids != sentinel)

        # Owner-side global dedup: the same id may arrive from many replicas.
        o_ids = jnp.where(owned, g_uids, sentinel)
        o_uids, o_inverse, _ = jnp.unique(
            o_ids, size=G, fill_value=sentinel, return_inverse=True,
            return_counts=True,
        )
        o_valid = o_uids != sentinel
        o_counts = (
            jnp.zeros((G,), jnp.int32)
            .at[o_inverse]
            .add(jnp.where(owned, g_counts, 0))
        )
        o_counts = jnp.where(o_valid, o_counts, 0)

        state, res = self.table._lookup_resolved(
            state, o_uids, o_counts, o_valid, step=step, train=train, salt=salt
        )

        # Back to gathered layout; non-owned rows contribute zero, then one
        # reduce-scatter hands each replica its own unique rows.
        e_g = res.embeddings[o_inverse] * owned[:, None].astype(res.embeddings.dtype)
        emb_local = jax.lax.psum_scatter(
            e_g.astype(jnp.float32), axis, scatter_dimension=0, tiled=True
        )  # [U, D]

        return state, ShardedLookup(
            inverse=inverse.reshape(ids.shape),
            counts=counts,
            valid=valid,
            embeddings=emb_local,
            owner_res=res,
            o_inverse=o_inverse,
            owned=owned,
        )

    def apply_gradients(
        self,
        state: TableState,
        opt: SparseOptimizer,
        sl: ShardedLookup,
        grad_u: jnp.ndarray,  # [U, D] grads w.r.t. sl.embeddings
        *,
        step: jnp.ndarray | int = 0,
        lr=None,
        grad_averaging: bool = False,
    ) -> TableState:
        g_g = jax.lax.all_gather(
            grad_u.astype(jnp.float32), self.axis, tiled=True
        )  # [G, D]
        G, D = g_g.shape
        o_grad = (
            jnp.zeros((G, D), jnp.float32)
            .at[sl.o_inverse]
            .add(g_g * sl.owned[:, None].astype(jnp.float32))
        )
        # Per-replica losses are means over the LOCAL batch (B/N); summing N
        # replicas' grads here would make the sparse step N x the
        # single-device one while dense grads get pmean'd. Rescale so both
        # paths see the global-batch-mean gradient.
        o_grad = o_grad / jnp.float32(self.num_shards)
        return optim_apply.apply_gradients(
            self.table,
            state,
            opt,
            sl.owner_res,
            o_grad,
            step=step,
            lr=lr,
            grad_averaging=grad_averaging,
        )
