"""Async embedding stage: stale-by-one decoupling of the embedding exchange
from dense compute.

DeepRec's AsyncEmbeddingStage (reference
tensorflow/python/training/async_embedding_stage.py, enabled by
config.proto:328 do_async_embedding) splits the graph at the embedding
boundary and runs the lookup subgraph in a pipeline stage, so the PS
round-trip for batch t+1 overlaps the dense compute of batch t; the model
consumes embeddings that are one step stale.

The TPU translation keeps the pipeline INSIDE one jitted step instead of
splitting the graph across threads. Each async step, in data-flow order:

  1. dense fwd/bwd on the CARRIED embeddings of batch t-1 (from AsyncState)
  2. collective lookup/exchange for batch t against the step-start tables
     — data-independent of (1), so XLA overlaps the all2all/allgather with
     the dense matmuls; this is the latency hiding the reference buys with
     its stage thread
  3. sparse-apply of batch t-1's gradients (after (1) and (2))
  4. dense optimizer update

Semantics (documented staleness, matching the reference):
  * the model sees embeddings fetched one step earlier;
  * sparse gradients are applied one step late, after the next batch's
    inserts (safe: inserts only claim empty slots, so the carried slot_ix
    stay valid — eviction/maintain() invalidates pending state and must be
    followed by `bootstrap()` on the next batch).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from deeprec_tpu.parallel.compat import shard_map
from deeprec_tpu.parallel.trainer import ShardedTrainer
from deeprec_tpu.training import metrics as M
from deeprec_tpu.training.trainer import PipelineCarry, TrainState

# The stale-by-one carry IS the generic pipeline carry (training/trainer.py):
# TrainState + one batch's prefetched lookup. The exact pipelined scan
# threads the same structure through its scan carry; the async stage is the
# degenerate (stale) version that finishes the lookup BEFORE the previous
# apply instead of after it.
AsyncState = PipelineCarry


class AsyncShardedTrainer(ShardedTrainer):
    """ShardedTrainer with the stale-by-one async embedding stage.

    Usage:
        astate = trainer.bootstrap(trainer.init(0), first_batch)
        for batch in batches:                    # feed batch t
            astate, mets = trainer.train_step_async(astate, batch)
        # mets at step t refer to batch t-1 (pipeline latency of one step)

    After maintain()/evict_tables() on astate.inner, call bootstrap() again:
    those rebuild tables and invalidate the carried slot indices.
    """

    def _make_jits(self):
        super()._make_jits()
        self._bootstrap_jit = jax.jit(self._bootstrap_impl)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps
        self._async_step = jax.jit(self._async_impl, donate_argnums=0)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps
        self._async_steps = jax.jit(self._async_steps_impl, donate_argnums=0)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps

    def _apply_one(self, b, state, res, grad, step, lr):
        # The stale-by-one apply consumes batch t-1's lookup result AFTER
        # batch t's lookup (and, across the scan, after t-1's own apply on
        # overlapping rows): the carried forward residual predates writes to
        # the same rows, so the apply must RE-GATHER (reuse_rows=False) —
        # and re-stamp version/dirty (stamp_meta=True), since the rows'
        # lookup-time stamps are a step old and a checkpoint's dirty-clear
        # may have landed in between.
        return self.sharded[b.name].apply_gradients(
            state, self.sparse_opt, res, grad, step=step, lr=lr,
            grad_averaging=self.grad_averaging,
            reuse_rows=False, stamp_meta=True,
        )

    # --------------------------------------------------------- bootstrap

    def bootstrap(self, state: TrainState, first_batch) -> AsyncState:
        """Fill the pipeline: lookup/exchange first_batch with no dense
        compute. The first train_step_async then consumes it."""
        return self._bootstrap_jit(state, first_batch)

    def _bootstrap_impl(self, state: TrainState, batch):
        state_spec, batch_spec = self._specs_for(state, batch)
        views_spec, res_spec, _ = self._carry_specs()

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec),
            out_specs=(state_spec, views_spec, res_spec),
            check_vma=False,
        )
        def run(state, batch):
            tables = {
                bname: self._squeeze(bname, ts)
                for bname, ts in state.tables.items()
            }
            # Split-phase lookup (route -> resolve -> finish) with
            # keep_rows=False: the stale apply never reuses the forward
            # residual (reuse_rows=False above), so the carried results
            # drop the owner-side [O, D] row buffer instead of hauling it
            # across dispatches and through the K-step scan carry.
            routes = self._route_all(batch, True)
            tables, pending = self._resolve_all(
                tables, routes, state.step, True
            )
            views, bundle_res = self._finish_all(
                tables, pending, batch, True, keep_rows=False
            )
            new_state = TrainState(
                step=state.step,
                tables={
                    bname: self._unsqueeze(bname, ts)
                    for bname, ts in tables.items()
                },
                dense=state.dense,
                opt_state=state.opt_state,
            )
            return new_state, views, bundle_res

        new_state, views, bundle_res = run(state, batch)
        return AsyncState(
            inner=new_state, batch=batch, views=views, bundle_res=bundle_res
        )

    # ------------------------------------------------------------- step

    def train_step_async(self, astate: AsyncState, batch, lr=None):
        lr = jnp.asarray(self.sparse_opt.lr if lr is None else lr, jnp.float32)
        return self._async_step(astate, batch, lr)

    def train_steps_async(self, astate: AsyncState, batches, lr=None):
        """K inner async steps per staged dispatch — the multi-step device
        loop composed with the stale-by-one embedding stage. `batches` is a
        list/tuple of K batch dicts (stacked + mesh-placed here) or a
        pre-placed [K, ...] pytree. Returns (astate, metrics[K]); metrics
        at inner step t refer to batch t-1, as in `train_step_async`."""
        from deeprec_tpu.parallel.mesh import shard_batch
        from deeprec_tpu.training.trainer import stack_batches

        if isinstance(batches, (list, tuple)):
            batches = shard_batch(
                self.mesh, stack_batches(batches), axis=self.axis,
                stacked=True,
            )
        lr = jnp.asarray(self.sparse_opt.lr if lr is None else lr, jnp.float32)
        return self._async_steps(astate, batches, lr)

    def _async_body(self, astate: AsyncState, batch_t, lr):
        """One async step on per-shard values (runs INSIDE shard_map).
        Shared by the single-step path and the K-step scan."""
        state = astate.inner
        step = state.step
        views = astate.views
        prev_batch = astate.batch

        # (1) dense fwd/bwd on the STALE embeddings (batch t-1)
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}

        def loss_fn(dense, embs):
            inputs = self._build_inputs(embs, views, prev_batch)
            out = self.model.apply(dense, inputs, train=True)
            loss, out = self._loss_from_logits(out, prev_batch)
            return loss, out

        (loss, out), (g_dense, g_embs) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True
        )(state.dense, embs)
        g_dense = jax.lax.pmean(g_dense, self.axis)

        # (2) exchange/lookup for batch t — reads the step-start tables,
        # no data dependency on (1): XLA overlaps it with the matmuls.
        # Expressed through the split-phase lookup; finish runs BEFORE the
        # stale apply below (that pre-apply gather IS the documented
        # staleness — the exact pipelined scan moves it after the apply).
        # keep_rows=False: the carried results never reuse the residual.
        tables = {
            bname: self._squeeze(bname, ts)
            for bname, ts in state.tables.items()
        }
        routes_t = self._route_all(batch_t, True)
        tables, pending_t = self._resolve_all(tables, routes_t, step, True)
        views_t, res_t = self._finish_all(
            tables, pending_t, batch_t, True, keep_rows=False
        )

        # (3) stale-apply batch t-1's sparse grads
        tables = self._apply_all(tables, astate.bundle_res, g_embs, step, lr)

        # (4) dense update
        updates, opt_state = self.dense_opt.update(
            g_dense, state.opt_state, state.dense
        )
        dense = optax.apply_updates(state.dense, updates)

        mets = {"loss": jax.lax.pmean(loss, self.axis)}
        if not isinstance(out, dict):
            probs = jax.nn.sigmoid(out)
            mets["accuracy"] = jax.lax.pmean(
                M.accuracy(probs, prev_batch["label"]), self.axis
            )
        else:
            mets["accuracy"] = jnp.zeros(())

        new_inner = TrainState(
            step=step + 1,
            tables={
                bname: self._unsqueeze(bname, ts)
                for bname, ts in tables.items()
            },
            dense=dense,
            opt_state=opt_state,
        )
        return (
            AsyncState(inner=new_inner, batch=batch_t, views=views_t,
                       bundle_res=res_t),
            mets,
        )

    def _astate_spec(self, state_spec):
        views_spec, res_spec, prev_batch_spec = self._carry_specs()
        return AsyncState(
            inner=state_spec, batch=prev_batch_spec, views=views_spec,
            bundle_res=res_spec,
        )

    def _async_impl(self, astate: AsyncState, batch_t, lr):
        state_spec, batch_spec = self._specs_for(astate.inner, batch_t)
        astate_spec = self._astate_spec(state_spec)
        out_metric_spec = {"loss": P(), "accuracy": P()}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(astate_spec, batch_spec, P()),
            out_specs=(astate_spec, out_metric_spec),
            check_vma=False,
        )
        def run(astate, batch_t, lr):
            return self._async_body(astate, batch_t, lr)

        return run(astate, batch_t, lr)

    def _async_steps_impl(self, astate: AsyncState, batches, lr):
        """K async steps per dispatch: lax.scan of `_async_body` inside one
        shard_map, threading the pipelined AsyncState (carried batch, views
        and lookup results of step t-1) through the scan carry — the
        stale-by-one semantics of every inner step are exactly those of K
        sequential `train_step_async` calls. Batches carry a leading
        unsharded [K] axis (`shard_batch(..., stacked=True)`)."""
        state_spec, _ = self._specs_for(astate.inner, {})
        astate_spec = self._astate_spec(state_spec)
        batch_spec = jax.tree.map(lambda _: P(None, self.axis), batches)
        out_metric_spec = {"loss": P(), "accuracy": P()}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(astate_spec, batch_spec, P()),
            out_specs=(astate_spec, out_metric_spec),
            check_vma=False,
        )
        def run(astate, batches, lr):
            def body(astate, batch_t):
                return self._async_body(astate, batch_t, lr)

            return jax.lax.scan(body, astate, batches)

        return run(astate, batches, lr)
