"""Skew-aware table placement for the sharded embedding exchange.

Uniform `hash_shard(id) % N` routing makes one shard the straggler of every
a2a/allgather under zipf traffic: the head of the distribution hammers its
hash-home, and small tables all park their head rows on the same shards.
DreamShard (PAPERS.md: "Generalizable Embedding Table Placement for
Recommender Systems") treats placement as a first-class cost-model
optimization and the RecShard line shows hot-key-aware partitioning is the
lever for zipf traffic; this module is that idea for the compiled-collective
exchange:

  * **`ShardPlan`** — per (member) table: an owner-offset rotation
    (`owner = (hash_shard(id) + offset) % N`, decorrelating tables that
    share a raw id space) plus a device-resident `[H]` hot-key routing
    table consulted BEFORE the hash (`plan_owner`): the top-H head keys
    get explicit greedily-balanced owners instead of their hash-home.
  * **Cost-model placer** (`build_plans`) — estimates each key's per-step
    exchange arrivals from the live freq counters (`TableState.meta`), the
    per-row wire bytes from `ops/traffic.py`, and greedily assigns offsets
    (best-rotation per table, heaviest table first) and hot-key owners
    (longest-processing-time to the least-loaded shard) to minimize the
    max-shard exchange load.
  * **Re-shard on plan change** (`reshard_members`) — rows whose owner
    moves migrate host-side through the same probe/pack machinery as
    rebuild/restore, bit-identically (placement changes WHERE a row lives,
    never its values), applied at a step boundary with the old plan
    serving until the swap (`ShardedTrainer.update_placement`).

Correctness contract: any single-owner routing yields bit-identical
training per key. Each source shard contributes at most one arrival per
key (local dedup precedes the exchange), arrivals land source-major in
both the allgather and a2a layouts, so a key's gradient contributions
sum in source-shard order under EVERY plan — the per-key optimizer math
cannot observe the placement. `tests/test_placement.py` pins this across
comm modes, the K-step scan and the pipelined lookahead.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from deeprec_tpu.utils import hashing


# ------------------------------------------------------------- device route


def plan_owner(ids: jnp.ndarray, num_shards: int, leaves=None) -> jnp.ndarray:
    """Owner shard of each id under a placement plan (device-side).

    `leaves` is the plan's device-constant dict ({} / None = uniform hash,
    compiling the identical program as before the plan subsystem existed):
      offset     []   int32  owner rotation
      hot_keys   [H]  key-dtype, sentinel-padded routing table
      hot_owners [H]  int32 explicit owners for the hot keys

    Consulted before `hash_shard`: hot keys take their table entry, every
    other id its rotated hash-home. Must stay bit-identical to
    `ShardPlan.owner_np` — checkpoint restore and plan migration route on
    the host with the same function.
    """
    base = hashing.hash_shard(ids, num_shards)
    if not leaves:
        return base
    owner = (base + jnp.asarray(leaves["offset"], jnp.int32)) % num_shards
    hk = leaves["hot_keys"]
    if hk.shape[-1]:
        eq = ids[:, None] == hk.astype(ids.dtype)[None, :]
        hot = jnp.any(eq, axis=1)
        hix = jnp.argmax(eq, axis=1)
        owner = jnp.where(
            hot, leaves["hot_owners"][hix].astype(jnp.int32), owner
        )
    return owner.astype(jnp.int32)


# --------------------------------------------------------------- plan types


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """Routing plan of ONE (member) table over `num_shards` shards.

    `hot_keys` must be unique real keys (never the sentinel); `sentinel`
    pads the device-side routing table out to the bundle's common H. The
    default plan (offset 0, no hot keys) routes exactly like the uniform
    hash."""

    num_shards: int
    sentinel: int
    offset: int = 0
    hot_keys: Tuple[int, ...] = ()
    hot_owners: Tuple[int, ...] = ()

    def __post_init__(self):
        assert len(self.hot_keys) == len(self.hot_owners)
        assert len(set(self.hot_keys)) == len(self.hot_keys), (
            "hot_keys must be unique (duplicate entries would make the "
            "device argmax and the host searchsorted disagree)"
        )

    @property
    def is_uniform(self) -> bool:
        return self.offset == 0 and not self.hot_keys

    def owner_np(self, keys) -> np.ndarray:
        """Host mirror of `plan_owner` (bit-identical): used by the
        checkpoint restore router and the migration path."""
        keys = np.asarray(keys)
        owner = (
            (hashing.hash_shard_np(keys, self.num_shards) + self.offset)
            % self.num_shards
        ).astype(np.int32)
        if self.hot_keys:
            hk = np.asarray(self.hot_keys, dtype=keys.dtype)
            ho = np.asarray(self.hot_owners, np.int32)
            order = np.argsort(hk, kind="stable")
            pos = np.clip(
                np.searchsorted(hk[order], keys), 0, len(order) - 1
            )
            cand = order[pos]
            hit = hk[cand] == keys
            owner = np.where(hit, ho[cand], owner).astype(np.int32)
        return owner

    def dest_hot_counts(self) -> np.ndarray:
        """[N] explicit hot-key arrivals this plan routes to each
        destination — the per-dest half of the a2a budget vector
        (`ops/traffic.py a2a_dest_budgets`): every source that sees a hot
        key sends it to the same planned owner, so the worst-case
        per-(source, dest) concentration IS this bincount."""
        return np.bincount(
            np.asarray(self.hot_owners, np.int64),
            minlength=self.num_shards,
        ).astype(np.int64)

    def leaves(self, key_dtype, pad_h: Optional[int] = None) -> Dict:
        """Device constants for `plan_owner`, hot arrays sentinel-padded
        to `pad_h` (stacked bundles need one common H across members)."""
        H = len(self.hot_keys) if pad_h is None else pad_h
        hk = np.full((H,), self.sentinel, dtype=key_dtype)
        ho = np.zeros((H,), np.int32)
        if self.hot_keys:
            hk[: len(self.hot_keys)] = np.asarray(
                self.hot_keys, dtype=key_dtype
            )
            ho[: len(self.hot_owners)] = np.asarray(
                self.hot_owners, np.int32
            )
        return {
            "offset": jnp.asarray(self.offset, jnp.int32),
            "hot_keys": jnp.asarray(hk),
            "hot_owners": jnp.asarray(ho),
        }


@dataclasses.dataclass(frozen=True)
class BundlePlan:
    """Per-member ShardPlans of one bundle (len T for stacked bundles,
    len 1 otherwise — shared-table bundles route every feature through
    the single member plan)."""

    plans: Tuple[ShardPlan, ...]

    def member(self, m: Optional[int]) -> ShardPlan:
        return self.plans[m or 0]

    @property
    def is_uniform(self) -> bool:
        return all(p.is_uniform for p in self.plans)

    def leaves(self, key_dtype, stacked: bool) -> Dict:
        """vmap-ready device constants: stacked bundles get a leading [T]
        member axis on every leaf (the lookup vmap maps over it), single
        tables the bare per-member leaves."""
        H = max((len(p.hot_keys) for p in self.plans), default=0)
        per = [p.leaves(key_dtype, pad_h=H) for p in self.plans]
        if not stacked:
            return per[0]
        return {
            k: jnp.stack([leaf[k] for leaf in per]) for k in per[0]
        }

    def dest_hot_counts(self) -> np.ndarray:
        """Elementwise max of the member plans' per-dest hot arrival
        counts — the bucket is shared by every vmapped member, so each
        destination budgets for its worst member."""
        out = np.zeros((self.plans[0].num_shards,), np.int64)
        for p in self.plans:
            out = np.maximum(out, p.dest_hot_counts())
        return out

    def hot_count_min(self) -> int:
        """Min hot-key count across members — the tail-share subtraction
        must hold for EVERY member riding the shared bucket, so only the
        keys every member's plan routes explicitly leave the tail."""
        return min((len(p.hot_keys) for p in self.plans), default=0)


# --------------------------------------------------- drift-driven replanning


@dataclasses.dataclass(frozen=True)
class ReplanConfig:
    """Knobs of the drift-driven replan trigger (`ShardedTrainer.
    maybe_replan`, run from maintain()). The discipline is the
    FleetAutoscaler's: hysteresis (sustain) so one noisy window never
    fires, cooldown so adoptions can't thrash, and an amortization
    horizon so the system replans exactly when the modeled gain pays for
    the modeled migration.

      threshold      windowed max-table imbalance (max/mean exchange
                     bytes) that counts as drift
      sustain        consecutive maintain() observations at/over the
                     threshold before the placer runs
      cooldown       maintain() calls after an adoption during which the
                     trigger stays quiet (migration just perturbed the
                     window; let the counters resettle)
      horizon_steps  steps over which the modeled straggler-bytes gain
                     must amortize the modeled migration bytes
                     (ops/traffic.py migration_bytes) for adoption
      min_gain       modeled-imbalance improvement factor required of a
                     candidate (the placement-v1 bar, kept as a second
                     hysteresis)
      window_secs    obs ring-buffer window consulted for the level/slope
                     (obs/metrics.py window queries)
      lead_secs      slope projection: a positive `window_slope` of the
                     imbalance gauge projected `lead_secs` ahead may
                     breach the threshold EARLY — the replan fires while
                     the drift is still building instead of after the
                     straggler fully forms (0 = level-only trigger)
    """

    threshold: float = 1.5
    sustain: int = 2
    cooldown: int = 2
    horizon_steps: int = 2000
    min_gain: float = 1.05
    window_secs: float = 120.0
    lead_secs: float = 0.0


class DriftDetector:
    """Pure host-side hysteresis gate over (level, slope) observations —
    one observe() per maintain(). Separated from the trainer so the
    trigger logic is unit-testable without a mesh
    (tests/test_placement_v2.py)."""

    def __init__(self, cfg: ReplanConfig):
        self.cfg = cfg
        self._breaches = 0
        self._cooldown = 0
        self.last: Dict[str, object] = {}

    def observe(self, level: float, slope: Optional[float] = None) -> bool:
        """Feed one windowed observation; True = run the placer now.
        `level` is the windowed max-table imbalance, `slope` its
        d/dt (None when the obs plane has <2 ring slots of history)."""
        cfg = self.cfg
        projected = level
        if slope is not None and slope > 0 and cfg.lead_secs > 0:
            projected = level + slope * cfg.lead_secs
        breach = level >= cfg.threshold or projected >= cfg.threshold
        self._breaches = self._breaches + 1 if breach else 0
        cooling = self._cooldown > 0
        if cooling:
            self._cooldown -= 1
        fire = (not cooling) and self._breaches >= cfg.sustain
        self.last = {
            "level": round(float(level), 4),  # noqa: DRT002 — host telemetry scalars by contract (maintain cadence, never traced)
            "slope_per_sec": (
                None if slope is None else round(float(slope), 6)  # noqa: DRT002 — host telemetry scalars by contract (maintain cadence, never traced)
            ),
            "projected": round(float(projected), 4),  # noqa: DRT002 — host telemetry scalars by contract (maintain cadence, never traced)
            "breaches": self._breaches,
            "cooldown": self._cooldown + (1 if cooling else 0),
            "fired": fire,
        }
        return fire

    def adopted(self) -> None:
        """A plan was adopted: start the cooldown, reset the breach run
        (the migration itself perturbs the next window's counters)."""
        self._cooldown = self.cfg.cooldown
        self._breaches = 0

    def deferred(self) -> None:
        """The placer ran but declined (min_gain / amortization): reset
        the breach run WITHOUT a cooldown — the trigger re-arms after
        another `sustain` breaching windows instead of re-running the
        placer every maintain() while the (unchanged) condition holds."""
        self._breaches = 0


def plan_moved_rows(
    members: Sequence["MemberTraffic"],
    current: Optional[Dict[Tuple[str, int], "ShardPlan"]],
    candidate: Dict[Tuple[str, int], "ShardPlan"],
) -> Dict[Tuple[str, int], int]:
    """Rows whose owner changes between two plan sets, per member —
    computed from the live key sets WITHOUT migrating (the amortization
    check needs the cost before deciding to pay it). Matches what
    `reshard_members` would move: a row migrates iff its owner under the
    candidate differs from its owner under the active plan."""
    out: Dict[Tuple[str, int], int] = {}
    for m in members:
        ref = (m.bundle, m.member)
        if ref not in candidate or len(m.keys) == 0:
            out[ref] = 0
            continue
        cur = (current or {}).get(ref)
        cur_owner = (
            cur.owner_np(m.keys) if cur is not None
            else hashing.hash_shard_np(m.keys, candidate[ref].num_shards)
        )
        out[ref] = int(np.sum(candidate[ref].owner_np(m.keys) != cur_owner))
    return out


# -------------------------------------------------------------- cost model


def modeled_loads(
    num_shards: int,
    members: Sequence["MemberTraffic"],
    plans: Optional[Dict[Tuple[str, int], ShardPlan]] = None,
) -> np.ndarray:
    """Modeled per-shard exchange load (bytes/step) of a set of member
    tables under `plans` (missing/None entries = uniform hash) — the
    quantity `build_plans` minimizes the max of, and what
    `update_placement` compares between the active and candidate plans."""
    L = np.zeros((num_shards,), np.float64)
    for m in members:
        if len(m.keys) == 0:
            continue
        plan = (plans or {}).get((m.bundle, m.member))
        owner = (
            plan.owner_np(m.keys)
            if plan is not None
            else hashing.hash_shard_np(m.keys, num_shards)
        )
        L += np.bincount(
            owner,
            weights=m.weight.astype(np.float64) * m.row_bytes,
            minlength=num_shards,
        )
    return L


@dataclasses.dataclass
class MemberTraffic:
    """Placer input for one member table: its live keys, each key's
    modeled exchange arrivals/step (min(freq/steps, N) — a key deduped on
    every source shard arrives at most N times), and the wire bytes one
    arrival row costs (`ops/traffic.py exchange_row_bytes`)."""

    bundle: str
    member: int
    keys: np.ndarray  # [n] live keys
    weight: np.ndarray  # [n] modeled arrivals per step
    row_bytes: float
    sentinel: int


def build_plans(
    num_shards: int,
    members: Sequence[MemberTraffic],
    *,
    hot_budget: int = 64,
    base_loads=None,
    cost_model=None,
    ambiguity: float = 1e-6,
) -> Tuple[Dict[Tuple[str, int], ShardPlan], Dict[str, object]]:
    """Greedy cost-model placer: minimize the max-shard exchange load.

    Two levers, applied heaviest-table-first against a running per-shard
    load vector L:
      1. **offset rotation** — each table's non-hot load lands at its
         hash-home rotated by r; the r minimizing max(L + rot(load, r))
         wins (this is what un-stacks tables sharing a raw id space,
         whose heads otherwise all hash to the same shards);
      2. **hot keys** — the top-`hot_budget` keys by modeled arrivals
         (only those worth moving: weight > 1, i.e. present on more than
         one source shard) are pulled out of the rotation and assigned
         LPT: heaviest first, each to the currently least-loaded shard.

    `base_loads` ([N], optional) is per-shard exchange load the placer
    must pack AROUND but cannot move — tables whose plan is pinned
    uniform (multi-tier storage keeps demoted rows in per-shard tier
    stores that don't migrate, so their routing must not change).

    `cost_model` (parallel/costmodel.py PlacementCostModel, optional) is
    the learned ranker: where the ANALYTIC rotation costs are ambiguous
    (within `ambiguity` relative of the best — ties are common once the
    running load vector is flat), a TRAINED model re-ranks the tied
    rotations by its calibrated per-shard load prediction. An untrained
    or absent model leaves every choice bit-identical to the analytic
    placer — the fallback contract
    (tests/test_placement_v2.py::test_cost_model_untrained_is_bit_identical).

    Returns (plans keyed by (bundle, member), report) where the report
    carries modeled per-shard loads and max/mean imbalance before (uniform
    hash) and after (the plan) — `bench.py --placement` then measures the
    same quantities from the live owner counters.
    """
    from deeprec_tpu.ops import traffic as T

    N = num_shards
    base = (
        np.zeros((N,), np.float64) if base_loads is None
        else np.asarray(base_loads, np.float64)
    )
    L = base.copy()
    L_before = base.copy()
    plans: Dict[Tuple[str, int], ShardPlan] = {}
    hot_all: List[Tuple[float, int, Tuple[str, int]]] = []
    hot_per: Dict[Tuple[str, int], List[Tuple[int, int]]] = {}
    offsets: Dict[Tuple[str, int], int] = {}

    order = sorted(
        members,
        key=lambda m: -float(np.sum(m.weight) * m.row_bytes),
    )
    for m in order:
        ref = (m.bundle, m.member)
        hot_per[ref] = []
        n = len(m.keys)
        if n == 0:
            offsets[ref] = 0
            continue
        base = hashing.hash_shard_np(m.keys, N)
        load = m.weight.astype(np.float64) * m.row_bytes
        L_before += np.bincount(base, weights=load, minlength=N)
        # Hot split: top-H by modeled arrivals, but only keys that arrive
        # from MORE than one shard — a weight<=1 key is already as cheap
        # as routing can make it, and spending routing-table slots on it
        # crowds out real head keys (the H-exceeded fallback contract).
        by_w = np.argsort(-m.weight, kind="stable")[: max(0, hot_budget)]
        hot_ix = by_w[m.weight[by_w] > 1.0]
        hot_mask = np.zeros((n,), bool)
        hot_mask[hot_ix] = True
        tail = np.bincount(
            base[~hot_mask], weights=load[~hot_mask], minlength=N
        )
        costs = [float(np.max(L + np.roll(tail, r))) for r in range(N)]
        best_r, best_cost = 0, float("inf")
        for r, cost in enumerate(costs):
            if cost < best_cost - 1e-9:
                best_r, best_cost = r, cost
        if cost_model is not None and cost_model.trained:
            # Learned re-rank of the analytic ties: rotations whose
            # analytic cost is indistinguishable from the winner's get
            # re-scored with the model's calibrated per-shard loads.
            # Deterministic: ties in the prediction fall back to the
            # analytic winner, then the smallest rotation.
            tol = abs(best_cost) * ambiguity + 1e-9
            tied = [r for r in range(N) if costs[r] <= best_cost + tol]
            if len(tied) > 1:
                stats = cost_model.member_stats(m)
                best_r = min(
                    tied,
                    key=lambda r: (
                        float(np.max(
                            L + cost_model.predict_loads(
                                stats, np.roll(tail, r)
                            )
                        )),
                        0 if r == best_r else 1,
                        r,
                    ),
                )
        offsets[ref] = best_r
        L += np.roll(tail, best_r)
        for i in hot_ix:
            hot_all.append((float(load[i]), int(m.keys[i]), ref))

    # LPT over every table's hot keys against the shared load vector.
    hot_all.sort(key=lambda t: (-t[0], t[1]))
    for w, key, ref in hot_all:
        s = int(np.argmin(L))
        L[s] += w
        hot_per[ref].append((key, s))

    for m in members:
        ref = (m.bundle, m.member)
        pairs = hot_per.get(ref, [])
        plans[ref] = ShardPlan(
            num_shards=N,
            sentinel=m.sentinel,
            offset=offsets.get(ref, 0),
            hot_keys=tuple(k for k, _ in pairs),
            hot_owners=tuple(s for _, s in pairs),
        )
    report = {
        "imbalance_before": round(T.shard_imbalance(L_before), 4),
        "imbalance_after": round(T.shard_imbalance(L), 4),
        "modeled_loads_before": [round(float(x), 1) for x in L_before],
        "modeled_loads_after": [round(float(x), 1) for x in L],
        "hot_keys": sum(len(v) for v in hot_per.values()),
    }
    return plans, report


# ---------------------------------------------------------------- re-shard


def reshard_members(
    table,
    shard_states,
    owner_np,
    slot_fills=None,
) -> Tuple[Optional[List], int, str]:
    """Move rows between the N per-shard states of ONE member table so
    every live key resides on `owner_np(key)`'s shard.

    Host-side, at maintain cadence — the same cadence as growth/eviction
    rebuilds. Rows migrate verbatim (values, fused meta, optimizer slot
    rows), so per-key training state is bit-identical before and after;
    transient counters reset (the rebuild contract); CBF sketches are
    rebuilt from the migrated freqs (the checkpoint re-shard fallback
    semantic: admitted keys exact, sub-threshold-only keys restart).

    Returns (new_states, moved, "") on success or (None, 0, reason) when
    any key cannot be placed (a shard over local capacity, or probe
    overflow) — the caller must then keep serving the OLD plan; nothing
    is mutated on failure.
    """
    from deeprec_tpu.embedding import filters as _filters
    from deeprec_tpu.embedding.table import (
        TableState, empty_key, empty_meta, probe_jit,
    )
    from deeprec_tpu.ops.packed import pack_array, unpack_array
    from deeprec_tpu.optim.sparse import SCALAR_PREFIX

    N = len(shard_states)
    cfg = table.cfg
    sent = empty_key(cfg)
    C = int(shard_states[0].keys.shape[0])

    all_keys, all_vals, all_meta, srcs = [], [], [], []
    slot_rows: Dict[str, List[np.ndarray]] = {}
    for s, st in enumerate(shard_states):
        keys = np.asarray(st.keys)
        occ = keys != sent
        if not occ.any():
            continue
        all_keys.append(keys[occ])
        srcs.append(np.full((int(occ.sum()),), s, np.int32))
        all_vals.append(np.asarray(unpack_array(st.values, C))[occ])
        all_meta.append(np.asarray(st.meta)[:, occ])
        for k, v in st.slots.items():
            if k.startswith(SCALAR_PREFIX):
                continue
            slot_rows.setdefault(k, []).append(
                np.asarray(unpack_array(v, C))[occ]
            )
    if not all_keys:
        return list(shard_states), 0, ""
    keys_g = np.concatenate(all_keys)
    srcs_g = np.concatenate(srcs)
    vals_g = np.concatenate(all_vals)
    meta_g = np.concatenate(all_meta, axis=1)
    slots_g = {k: np.concatenate(v) for k, v in slot_rows.items()}
    owners = np.asarray(owner_np(keys_g), np.int32)
    moved = int(np.sum(owners != srcs_g))

    fills = dict(slot_fills or ())
    new_states: List[TableState] = []
    for s in range(N):
        sel = owners == s
        ks = keys_g[sel]
        if ks.size > C:
            return None, 0, (
                f"shard {s} would hold {ks.size} keys > local capacity {C}"
            )
        old = shard_states[s]
        kdt = np.asarray(old.keys).dtype
        uids = np.full((C,), sent, dtype=kdt)
        uids[: ks.size] = ks
        uids_j = jnp.asarray(uids)
        new_keys, slot_ix, _, failed = probe_jit(
            table, jnp.full((C,), sent, old.keys.dtype), uids_j,
            uids_j != jnp.asarray(sent, old.keys.dtype),
        )
        if int(jnp.sum(failed)):
            return None, 0, f"shard {s}: probe overflow at load {ks.size}/{C}"
        six = jnp.asarray(np.asarray(slot_ix)[: ks.size])

        def place(rows_np, fill, width):
            arr = jnp.full(
                (C, width), fill, dtype=jnp.asarray(rows_np).dtype
            )
            return arr.at[six].set(jnp.asarray(rows_np))

        vals_new = pack_array(
            place(vals_g[sel], 0, vals_g.shape[1]),
            table.pack_width(vals_g.shape[1], C),
        )
        meta_new = empty_meta(C).at[:, six].set(jnp.asarray(meta_g[:, sel]))
        slots_new = {}
        for k, v in old.slots.items():
            if k.startswith(SCALAR_PREFIX):
                slots_new[k] = v
                continue
            rows = slots_g[k][sel]
            slots_new[k] = pack_array(
                place(rows, fills.get(k, 0), rows.shape[1]),
                table.pack_width(rows.shape[1], C),
            )
        bloom = old.bloom
        if bloom is not None and cfg.ev.cbf_filter is not None:
            bloom, _ = _filters.cbf_add(
                cfg.ev.cbf_filter, jnp.zeros_like(bloom),
                jnp.asarray(uids[: ks.size]),
                jnp.asarray(meta_g[0, sel], jnp.int32),
            )
        new_states.append(TableState(
            keys=new_keys,
            values=vals_new,
            meta=meta_new,
            slots=slots_new,
            bloom=bloom,
            insert_fails=jnp.zeros((), jnp.int32),
        ))
    return new_states, moved, ""
