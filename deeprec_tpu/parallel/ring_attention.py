"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long sequences shard over the `sp` axis; each device holds its local Q/K/V
slice and K/V blocks rotate around the ring via lax.ppermute (XLA lowers the
rotation to ICI neighbor transfers that overlap with the local attention
compute). Online-softmax accumulation keeps the math exact across steps —
this is standard ring attention, giving O(L/P) activation memory per device
and near-linear scaling of context length with ring size.

DeepRec has no sequence parallelism (SURVEY.md §5: "long-context: not
present") — this is a capability the TPU framework adds because long
behavior histories (SIM-style) need it at scale.

Call inside shard_map with Q/K/V sharded on the sequence axis:
    shard_map(..., in_specs=P(None, None, 'sp', None))(ring_attention)
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,  # [B, H, Lq_local, D]
    k: jnp.ndarray,  # [B, H, S_local, D]
    v: jnp.ndarray,  # [B, H, S_local, D]
    mask: jnp.ndarray,  # [B, S_local] bool
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact attention over the full (sharded) sequence. Differentiable via
    autodiff through the ppermute ring (grads flow the reverse ring)."""
    B, H, Lq, D = q.shape
    S = k.shape[2]
    P = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    qf = q.astype(jnp.float32)

    # Global positions of the local Q rows (for causal masking across shards).
    qpos = me * Lq + jax.lax.broadcasted_iota(jnp.int32, (Lq, S), 0)

    def step(carry, r):
        m, l, acc, ks, vs, mk, src = carry
        # src = shard that originally owned the current K/V block
        s = jnp.einsum("bhld,bhsd->bhls", qf, ks.astype(jnp.float32)) * scale
        s = jnp.where(mk[:, None, None, :], s, NEG_INF)
        if causal:
            kpos = src * S + jax.lax.broadcasted_iota(jnp.int32, (Lq, S), 1)
            s = jnp.where((kpos <= qpos)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum(
            "bhls,bhsd->bhld", p, vs.astype(jnp.float32)
        )
        # rotate K/V/mask/owner one hop around the ring
        perm = [(i, (i + 1) % P) for i in range(P)]
        ks = jax.lax.ppermute(ks, axis_name, perm)
        vs = jax.lax.ppermute(vs, axis_name, perm)
        mk = jax.lax.ppermute(mk, axis_name, perm)
        src = jax.lax.ppermute(src, axis_name, perm)
        return (m_new, l, acc, ks, vs, mk, src), None

    m0 = jnp.full((B, H, Lq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Lq, 1), jnp.float32)
    a0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    carry = (m0, l0, a0, k, v, mask, me)
    (m, l, acc, *_), _ = jax.lax.scan(step, carry, jnp.arange(P))
    l_safe = jnp.maximum(l, 1e-30)
    return (acc / l_safe).astype(q.dtype)


def ring_attention_sharded(
    mesh, q, k, v, mask, axis: str = "sp", causal: bool = False,
):
    """Convenience wrapper: shard_map over `axis` with Q/K/V/mask sequence-
    sharded, output sequence-sharded."""
    from jax.sharding import PartitionSpec as P

    from deeprec_tpu.parallel.compat import shard_map

    fn = functools.partial(ring_attention, axis_name=axis, causal=causal)
    seq = P(None, None, axis, None)
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(seq, seq, seq, P(None, axis)),
        out_specs=seq,
        check_vma=False,
    )(q, k, v, mask)
