"""shard_map compatibility across jax versions.

jax >= 0.4.35 exposes ``jax.shard_map``; newer versions renamed the
replication-check flag ``check_rep`` -> ``check_vma``. Callers here write
the modern spelling (``check_vma=``); this wrapper translates to whatever
the resident jax accepts, so the sharded trainers run on both old and new
runtimes without every call site carrying a try/except.
"""
from __future__ import annotations

import inspect

import jax

try:
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# Which spelling of the replication-check flag does this jax accept?
_PARAMS = frozenset(inspect.signature(_shard_map).parameters)
if "check_vma" in _PARAMS:
    _CHECK_KW = "check_vma"
elif "check_rep" in _PARAMS:
    _CHECK_KW = "check_rep"
else:  # pragma: no cover - flag dropped entirely
    _CHECK_KW = None


def shard_map(f, **kw):
    """``jax.shard_map`` accepting either check_vma= or check_rep=."""
    for alias in ("check_vma", "check_rep"):
        if alias in kw and alias != _CHECK_KW:
            val = kw.pop(alias)
            if _CHECK_KW is not None:
                kw.setdefault(_CHECK_KW, val)
    return _shard_map(f, **kw)
