"""SPMD trainer: whole-train-step shard_map over the `data` axis.

One compiled program per step does: batch-parallel dense forward/backward
(grads psum'd over ICI), hash-sharded table lookups (all_gather ids +
reduce-scatter embeddings) and owner-side fused sparse applies. This replaces
DeepRec's worker/PS process split (SURVEY.md §3.2) — there is no separate
parameter process; the "PS" is the sharded table arrays resident in each
chip's HBM, and the "RPC" is compiled collectives.

Bundled (GroupEmbedding) tables vmap the collective lookup over the table
axis, so the ids of N tables ride ONE batched all_gather and their embeddings
ONE batched reduce-scatter — the same batching trick as DeepRec's grouped SOK
lookup (docs/docs_en/Group-Embedding.md).

Usable identically on a real TPU mesh or on N virtual CPU devices
(XLA_FLAGS=--xla_force_host_platform_device_count=N) — the reference tests
distributed behavior with in-process fake clusters the same way (SURVEY §4).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeprec_tpu.parallel.compat import shard_map

from deeprec_tpu import features as fcol
from deeprec_tpu.embedding.table import EmbeddingTable
from deeprec_tpu.optim.apply import ensure_slots
from deeprec_tpu.parallel.sharded import ShardedTable
from deeprec_tpu.training import metrics as M
from deeprec_tpu.training.trainer import (
    Bundle,
    ModelInputs,
    Trainer,
    TrainState,
    _prep_ids,
    build_bundles,
    stack_batches,
)


def _local_cfg(cfg, num_shards: int):
    assert cfg.capacity % num_shards == 0, (
        f"table {cfg.name}: capacity {cfg.capacity} not divisible by mesh size"
    )
    return dataclasses.replace(cfg, capacity=cfg.capacity // num_shards)


class ShardedTrainer(Trainer):
    """Drop-in Trainer over a device mesh: tables hash-sharded, batch split."""

    def __init__(
        self,
        model,
        sparse_opt,
        dense_opt: Optional[optax.GradientTransformation] = None,
        mesh: Optional[Mesh] = None,
        axis: str = "data",
        grad_averaging: bool = False,
        comm: str = "allgather",  # or "a2a": budgeted all2all (SOK path)
        remat: bool = False,
        a2a_slack: float = 2.0,
        unique_budget=None,
        pipeline_mode: str = "off",
        pipeline_chunks: int = 4,
    ):
        from deeprec_tpu.parallel.mesh import make_mesh

        self.mesh = mesh or make_mesh(axis=axis)
        self.axis = axis
        self.num_shards = self.mesh.devices.size
        super().__init__(model, sparse_opt, dense_opt, grad_averaging, remat,
                         unique_budget=unique_budget,
                         pipeline_mode=pipeline_mode,
                         pipeline_chunks=pipeline_chunks)
        # Re-point bundles at per-shard capacities + collective wrappers.
        # pipeline_mode="chunked" splits each table's value/grad exchanges
        # into pipeline_chunks column chunks (ShardedTable.exchange_chunks)
        # on EVERY train path (single-step and K-step scan) — bitwise
        # identical arithmetic, overlappable wire.
        chunks = pipeline_chunks if pipeline_mode == "chunked" else 1
        for bname, b in self.bundles.items():
            b.table = EmbeddingTable(_local_cfg(b.table.cfg, self.num_shards))
        self.sharded = {
            bname: ShardedTable(b.table, self.num_shards, axis, comm=comm,
                                a2a_slack=a2a_slack, exchange_chunks=chunks)
            for bname, b in self.bundles.items()
        }

    def _make_jits(self):
        # Called by Trainer.__init__ (before self.sharded exists — jit
        # wrapping is lazy) and by update_budgets on a budget change.
        self._train_step = jax.jit(self._sharded_step, donate_argnums=0)
        self._train_step_accum = jax.jit(self._sharded_accum, donate_argnums=0)
        self._train_steps = jax.jit(self._sharded_steps, donate_argnums=0)
        self._eval_step = jax.jit(self._sharded_eval)

    def _stage_put(self, batch):
        # auto-stage (Trainer.stage) places batches with mesh sharding so
        # the staged transfer already lands split across devices
        from deeprec_tpu.parallel.mesh import shard_batch

        return shard_batch(self.mesh, batch, axis=self.axis)

    # ------------------------------------------------------------------ init

    def init(self, seed: int = 0) -> TrainState:
        from deeprec_tpu.parallel.mesh import put_global, put_tiled_global

        key = jax.random.PRNGKey(seed)
        dense = self.model.init(key)
        N = self.num_shards
        tables = {}
        for bname, b in self.bundles.items():
            local = ensure_slots(b.table, b.table.create(), self.sparse_opt)
            # layout: [T?, N, C_local, ...] — shard axis right before
            # capacity. The per-shard template tiles identically along the
            # lead axes; put_tiled_global never materializes the pod-scale
            # global value on one host.
            if b.stacked:
                lead = (len(b.features), N)
                spec = P(None, self.axis)
            else:
                lead = (N,)
                spec = P(self.axis)
            sh = NamedSharding(self.mesh, spec)
            tables[bname] = jax.tree.map(
                lambda a, lead=lead, s=sh: put_tiled_global(a, lead, s), local
            )
        repl = NamedSharding(self.mesh, P())
        put_repl = lambda t: jax.tree.map(lambda a: put_global(a, repl), t)
        return TrainState(
            step=put_global(jnp.zeros((), jnp.int32), repl),
            tables=tables,
            dense=put_repl(dense),
            opt_state=put_repl(self.dense_opt.init(dense)),
        )

    # -------------------------------------------------------------- internals

    def _table_spec(self, bname):
        b = self.bundles[bname]
        return P(None, self.axis) if b.stacked else P(self.axis)

    def _specs_for(self, state: TrainState, batch):
        ax = self.axis
        state_spec = TrainState(
            step=P(),
            tables={
                bname: jax.tree.map(lambda _: self._table_spec(bname), ts)
                for bname, ts in state.tables.items()
            },
            dense=jax.tree.map(lambda _: P(), state.dense),
            opt_state=jax.tree.map(lambda _: P(), state.opt_state),
        )
        batch_spec = jax.tree.map(lambda _: P(ax), batch)
        return state_spec, batch_spec

    def _squeeze(self, bname, ts):
        ax = 1 if self.bundles[bname].stacked else 0
        return jax.tree.map(lambda a: jnp.squeeze(a, axis=ax), ts)

    def _unsqueeze(self, bname, ts):
        ax = 1 if self.bundles[bname].stacked else 0
        return jax.tree.map(lambda a: jnp.expand_dims(a, axis=ax), ts)

    def _evict_bundle(self, b, ts, step):
        # leading dims: [T?, N, C]; evict each shard's local table
        fills = self._slot_fills(b)
        fn = lambda s: b.table.evict(s, step, slot_fills=fills)
        fn = jax.vmap(fn)  # over shards
        if b.stacked:
            fn = jax.vmap(fn)  # over grouped tables
        return fn(ts)

    # Per-bundle primitives: the only thing that differs from the base
    # Trainer is that lookup/apply go through the collective ShardedTable.
    # The unique budget resolves on the LOCAL batch — dedup-at-budget runs
    # before the exchange, so the a2a payload / allgather return shrink by
    # the same U/N factor as the compute.
    def _budget_capacity(self, b):
        # The bundle's cfg is the PER-SHARD capacity; local-batch uniques
        # are bounded by the global table (they hash across all shards).
        return b.table.cfg.capacity * self.num_shards

    def _lookup_one(self, b, state, ids, pad, salt, step, train):
        U = self._budget_for_lookup(b, ids, train)
        return self.sharded[b.name].lookup_unique(
            state, ids, step=step, train=train, pad_value=pad, salt=salt,
            unique_size=U,
        )

    def _apply_one(self, b, state, res, grad, step, lr):
        # Sync sharded hot path: traffic-diet opt-in (see Trainer._apply_one).
        return self.sharded[b.name].apply_gradients(
            state, self.sparse_opt, res, grad, step=step, lr=lr,
            grad_averaging=self.grad_averaging,
            reuse_rows=self._bundle_reuse_rows(b), stamp_meta=False,
        )

    # Split-phase primitives (Trainer._route_all/_resolve_all/_finish_all
    # drive these): the collective versions — route carries the id
    # exchange, finish the embedding exchange.
    def _route_one(self, b, ids, pad, train):
        U = self._budget_for_lookup(b, ids, train)
        return self.sharded[b.name].route(
            ids, pad_value=pad, unique_size=U
        )

    def _resolve_one(self, b, state, route, salt, step, train):
        return self.sharded[b.name].resolve(
            state, route, step=step, train=train, salt=salt
        )

    def _finish_one(self, b, state, pending, train, keep_rows=True):
        return self.sharded[b.name].finish(
            state, pending, train=train, keep_rows=keep_rows
        )

    def _carry_specs(self):
        """Prefix spec trees for a PipelineCarry's lookahead halves
        (shard_map broadcasts a spec over a subtree): views/batch leaves
        shard the leading local axis; stacked bundles carry their table
        axis first. Used where a carry crosses the shard_map boundary —
        the async stale-by-one stage (parallel/async_stage.py); the exact
        pipelined scan keeps its carry inside one shard_map region."""
        ax = self.axis
        views_spec = P(ax)
        res_spec = {
            bname: P(None, ax) if b.stacked else P(ax)
            for bname, b in self.bundles.items()
        }
        batch_spec = P(ax)
        return views_spec, res_spec, batch_spec

    # --------------------------------------------- capacity management

    def _bundle_lead_dims(self, b):
        # [T?, N, C_local]: members iterate grouped tables × shards.
        T = (len(b.features),) if b.stacked else ()
        return T + (self.num_shards,)

    def _set_bundle_capacity(self, b, new_c):
        super()._set_bundle_capacity(b, new_c)
        # Re-point the collective wrapper at the grown local table.
        old = self.sharded[b.name]
        self.sharded[b.name] = ShardedTable(
            b.table, old.num_shards, old.axis, comm=old.comm,
            a2a_slack=old.a2a_slack, exchange_chunks=old.exchange_chunks,
        )

    def maintain(self, state, **kw):
        # max_capacity is the GLOBAL cap; the base loop compares against
        # per-shard local capacities.
        if kw.get("max_capacity"):
            kw["max_capacity"] = max(1, kw["max_capacity"] // self.num_shards)
        state, report = super().maintain(state, **kw)
        # Growth changed per-shard shapes: restore the mesh sharding the
        # step functions expect (host-side stacking produced unsharded
        # arrays).
        from jax.sharding import NamedSharding

        tables = {}
        for bname, ts in state.tables.items():
            spec = self._table_spec(bname)
            tables[bname] = jax.device_put(
                ts, NamedSharding(self.mesh, spec)
            )
        return (
            TrainState(step=state.step, tables=tables, dense=state.dense,
                       opt_state=state.opt_state),
            report,
        )

    # ------------------------------------------------------------------ steps

    def _sharded_micro(self, tables, dense, batch, step, lr):
        """One (micro-)batch inside shard_map: lookups, fwd/bwd, sparse
        applies; returns tables, pmean'd dense grads (unapplied), metrics."""
        with jax.named_scope("phase_lookup_exchange"):
            tables, views, bundle_res = self._lookup_all(
                tables, batch, step, True
            )
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}

        def loss_fn(dense, embs):
            inputs = self._build_inputs(embs, views, batch)
            out = self.model.apply(dense, inputs, train=True)
            loss, out = self._loss_from_logits(out, batch)
            return loss, out

        with jax.named_scope("phase_dense_fwd_bwd"):
            (loss, out), (g_dense, g_embs) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True
            )(dense, embs)
        # Data-parallel dense grads: mean over replicas via ICI allreduce.
        g_dense = jax.lax.pmean(g_dense, self.axis)
        with jax.named_scope("phase_sparse_apply"):
            tables = self._apply_all(tables, bundle_res, g_embs, step, lr)

        mets = {"loss": jax.lax.pmean(loss, self.axis)}
        if not isinstance(out, dict):
            probs = jax.nn.sigmoid(out)
            mets["accuracy"] = jax.lax.pmean(
                M.accuracy(probs, batch["label"]), self.axis
            )
        else:
            mets["accuracy"] = jnp.zeros(())
        return tables, g_dense, mets

    def _sharded_body(self, state: TrainState, batch, lr):
        """One full train step on per-shard values (runs INSIDE shard_map):
        squeeze the shard axis off the tables, micro-step, dense update,
        re-wrap. Shared by the single-step path and the K-step scan."""
        step = state.step
        tables = {
            bname: self._squeeze(bname, ts)
            for bname, ts in state.tables.items()
        }
        tables, g_dense, mets = self._sharded_micro(
            tables, state.dense, batch, step, lr
        )
        updates, opt_state = self.dense_opt.update(
            g_dense, state.opt_state, state.dense
        )
        dense = optax.apply_updates(state.dense, updates)
        new_state = TrainState(
            step=step + 1,
            tables={
                bname: self._unsqueeze(bname, ts)
                for bname, ts in tables.items()
            },
            dense=dense,
            opt_state=opt_state,
        )
        return new_state, mets

    def _sharded_step(self, state: TrainState, batch, lr):
        state_spec, batch_spec = self._specs_for(state, batch)
        out_metric_spec = {"loss": P(), "accuracy": P()}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec, P()),
            out_specs=(state_spec, out_metric_spec),
            check_vma=False,
        )
        def run(state, batch, lr):
            return self._sharded_body(state, batch, lr)

        return run(state, batch, lr)

    def _sharded_steps(self, state: TrainState, batches, lr):
        """K-step device loop (Trainer._steps_impl mirror): one shard_map
        whose body scans `_sharded_body` over the K-stacked batch — the
        a2a/allgather exchange of every inner step stays inside the single
        compiled program, so K steps cost one host dispatch. Batch leaves
        are [K, B, ...] with the K axis unsharded and the batch axis split
        over the mesh (`shard_batch(..., stacked=True)`).

        pipeline_mode != "off" routes to the rotated scan
        (`_sharded_steps_pipelined`): same semantics, bit-exact, with the
        id exchange + owner probe of batch t+1 hoisted over batch t's
        dense compute."""
        if self.pipeline_mode != "off":
            return self._sharded_steps_pipelined(state, batches, lr)
        state_spec, _ = self._specs_for(state, {})
        batch_spec = jax.tree.map(lambda _: P(None, self.axis), batches)
        out_metric_spec = {"loss": P(), "accuracy": P()}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec, P()),
            out_specs=(state_spec, out_metric_spec),
            check_vma=False,
        )
        def run(state, batches, lr):
            def body(state, batch):
                return self._sharded_body(state, batch, lr)

            return jax.lax.scan(body, state, batches)

        return run(state, batches, lr)

    # -------------------------------------------- pipelined K-step scan

    def _sharded_pipe_prologue(self, state: TrainState, batch0):
        """Fill the pipeline inside shard_map: split-phase lookup of the
        window's first batch (same program as the sequential lookup)."""
        from deeprec_tpu.training.trainer import PipelineCarry

        tables = {
            bname: self._squeeze(bname, ts)
            for bname, ts in state.tables.items()
        }
        routes = self._route_all(batch0, True)
        tables, pending = self._resolve_all(tables, routes, state.step, True)
        views, res = self._finish_all(tables, pending, batch0, True)
        new_state = TrainState(
            step=state.step,
            tables={
                bname: self._unsqueeze(bname, ts)
                for bname, ts in tables.items()
            },
            dense=state.dense,
            opt_state=state.opt_state,
        )
        return PipelineCarry(inner=new_state, batch=batch0, views=views,
                             bundle_res=res)

    def _sharded_pipe_step(self, carry, batch_next, lr):
        """One pipelined sharded step on per-shard values (inside
        shard_map) — `Trainer._pipe_step` with the collective split
        phases and pmean'd dense grads/metrics:

          1. route(t+1): id dedup + id a2a/allgather + owner dedup —
             ids-only, issued before the dense compute so the async
             collective hides behind the matmuls;
          2. resolve(t+1): owner probe/insert + fused metadata + init —
             keys/meta only, commutes bit-exactly with apply(t);
          3. dense fwd/bwd on the carried lookup of batch t;
          4. grad exchange + sparse apply of batch t;
          5. finish(t+1): owner value gather + embedding exchange, AFTER
             the apply — batch t+1 sees post-apply tables, zero staleness.

        batch_next=None: window epilogue, only `.inner` of the returned
        carry is meaningful."""
        from deeprec_tpu.training.trainer import PipelineCarry

        state = carry.inner
        step = state.step
        tables = {
            bname: self._squeeze(bname, ts)
            for bname, ts in state.tables.items()
        }
        if batch_next is not None:
            with jax.named_scope("phase_route_next"):
                routes = self._route_all(batch_next, True)
                tables, pending = self._resolve_all(
                    tables, routes, step + 1, True
                )
        views = carry.views
        prev_batch = carry.batch
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}

        def loss_fn(dense, embs):
            inputs = self._build_inputs(embs, views, prev_batch)
            out = self.model.apply(dense, inputs, train=True)
            loss, out = self._loss_from_logits(out, prev_batch)
            return loss, out

        with jax.named_scope("phase_dense_fwd_bwd"):
            (loss, out), (g_dense, g_embs) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True
            )(state.dense, embs)
        g_dense = jax.lax.pmean(g_dense, self.axis)
        with jax.named_scope("phase_sparse_apply"):
            tables = self._apply_all(tables, carry.bundle_res, g_embs, step, lr)
        if batch_next is not None:
            with jax.named_scope("phase_finish_exchange"):
                views_n, res_n = self._finish_all(
                    tables, pending, batch_next, True
                )
        else:
            batch_next, views_n, res_n = prev_batch, views, carry.bundle_res
        updates, opt_state = self.dense_opt.update(
            g_dense, state.opt_state, state.dense
        )
        dense = optax.apply_updates(state.dense, updates)
        mets = {"loss": jax.lax.pmean(loss, self.axis)}
        if not isinstance(out, dict):
            probs = jax.nn.sigmoid(out)
            mets["accuracy"] = jax.lax.pmean(
                M.accuracy(probs, prev_batch["label"]), self.axis
            )
        else:
            mets["accuracy"] = jnp.zeros(())
        new_state = TrainState(
            step=step + 1,
            tables={
                bname: self._unsqueeze(bname, ts)
                for bname, ts in tables.items()
            },
            dense=dense,
            opt_state=opt_state,
        )
        return PipelineCarry(
            inner=new_state, batch=batch_next, views=views_n,
            bundle_res=res_n,
        ), mets

    def _sharded_steps_pipelined(self, state: TrainState, batches, lr):
        """The rotated K-step scan: prologue lookup of batch 0, a scan
        whose carry threads the one-batch lookahead (PipelineCarry — it
        never crosses the shard_map boundary, so it needs no specs), and a
        peeled epilogue for the last batch (which has nothing to
        prefetch; peeling keeps the final table state bit-identical — a
        masked dummy resolve would insert phantom keys)."""
        state_spec, _ = self._specs_for(state, {})
        batch_spec = jax.tree.map(lambda _: P(None, self.axis), batches)
        out_metric_spec = {"loss": P(), "accuracy": P()}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec, P()),
            out_specs=(state_spec, out_metric_spec),
            check_vma=False,
        )
        def run(state, batches, lr):
            batch0 = jax.tree.map(lambda x: x[0], batches)
            rest = jax.tree.map(lambda x: x[1:], batches)
            carry = self._sharded_pipe_prologue(state, batch0)

            def body(carry, batch_next):
                return self._sharded_pipe_step(carry, batch_next, lr)

            carry, mets = jax.lax.scan(body, carry, rest)
            carry, tail = self._sharded_pipe_step(carry, None, lr)
            mets = jax.tree.map(
                lambda a, b: jnp.concatenate([a, b[None]]), mets, tail
            )
            return carry.inner, mets

        return run(state, batches, lr)

    def _sharded_accum(self, state: TrainState, batch, lr):
        """Micro-batched sharded step: batch leaves [A, B_local*N, ...] — the
        accumulation axis is unsharded, the batch axis splits across the
        mesh; lax.scan over micro-batches inside the shard_map."""
        state_spec, _ = self._specs_for(state, {})
        batch_spec = jax.tree.map(lambda _: P(None, self.axis), batch)
        out_metric_spec = {"loss": P(), "accuracy": P()}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec, P()),
            out_specs=(state_spec, out_metric_spec),
            check_vma=False,
        )
        def run(state, batch, lr):
            step = state.step
            A = next(iter(batch.values())).shape[0]
            tables0 = {
                bname: self._squeeze(bname, ts)
                for bname, ts in state.tables.items()
            }

            def micro(carry, mb):
                tables, g_acc = carry
                tables, g_dense, mets = self._sharded_micro(
                    tables, state.dense, mb, step, lr
                )
                return (tables, jax.tree.map(jnp.add, g_acc, g_dense)), mets

            g0 = jax.tree.map(jnp.zeros_like, state.dense)
            (tables, g_acc), mets = jax.lax.scan(micro, (tables0, g0), batch)
            g_mean = jax.tree.map(lambda g: g / jnp.float32(A), g_acc)
            updates, opt_state = self.dense_opt.update(
                g_mean, state.opt_state, state.dense
            )
            dense = optax.apply_updates(state.dense, updates)
            new_state = TrainState(
                step=step + 1,
                tables={
                    bname: self._unsqueeze(bname, ts)
                    for bname, ts in tables.items()
                },
                dense=dense,
                opt_state=opt_state,
            )
            return new_state, jax.tree.map(jnp.mean, mets)

        return run(state, batch, lr)

    def train_steps(self, state: TrainState, batches, lr=None):
        """K steps per dispatch on the mesh. A list/tuple of batch dicts is
        stacked and placed with the K axis unsharded and the batch axis
        split (P(None, axis)); pass a pre-placed stacked pytree
        (`shard_batch(..., stacked=True)`) to skip the host round-trip."""
        if isinstance(batches, (list, tuple)):
            from deeprec_tpu.parallel.mesh import shard_batch

            batches = shard_batch(
                self.mesh, stack_batches(batches), axis=self.axis,
                stacked=True,
            )
        return super().train_steps(state, batches, lr)

    def _sharded_eval(self, state: TrainState, batch):
        state_spec, batch_spec = self._specs_for(state, batch)

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec),
            out_specs=(P(), P(self.axis)),
            check_vma=False,
        )
        def run(state, batch):
            tables = {
                bname: self._squeeze(bname, ts)
                for bname, ts in state.tables.items()
            }
            tables, views, _ = self._lookup_all(
                tables, batch, state.step, False
            )
            embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}
            inputs = self._build_inputs(embs, views, batch)
            out = self.model.apply(state.dense, inputs, train=False)
            loss, out = self._loss_from_logits(out, batch)
            probs = (
                {k: jax.nn.sigmoid(v) for k, v in out.items()}
                if isinstance(out, dict)
                else jax.nn.sigmoid(out)
            )
            return jax.lax.pmean(loss, self.axis), probs

        return run(state, batch)
