"""SPMD trainer: whole-train-step shard_map over the `data` axis.

One compiled program per step does: batch-parallel dense forward/backward
(grads psum'd over ICI), hash-sharded table lookups (all_gather ids +
reduce-scatter embeddings) and owner-side fused sparse applies. This replaces
DeepRec's worker/PS process split (SURVEY.md §3.2) — there is no separate
parameter process; the "PS" is the sharded table arrays resident in each
chip's HBM, and the "RPC" is compiled collectives.

Bundled (GroupEmbedding) tables vmap the collective lookup over the table
axis, so the ids of N tables ride ONE batched all_gather and their embeddings
ONE batched reduce-scatter — the same batching trick as DeepRec's grouped SOK
lookup (docs/docs_en/Group-Embedding.md).

Usable identically on a real TPU mesh or on N virtual CPU devices
(XLA_FLAGS=--xla_force_host_platform_device_count=N) — the reference tests
distributed behavior with in-process fake clusters the same way (SURVEY §4).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeprec_tpu.parallel.compat import shard_map

from deeprec_tpu.embedding.table import EmbeddingTable
from deeprec_tpu.optim.apply import ensure_slots
from deeprec_tpu.parallel import placement as placement_lib
from deeprec_tpu.parallel.mesh import DATA_AXIS
from deeprec_tpu.parallel.placement import BundlePlan
from deeprec_tpu.parallel.sharded import ShardedTable
from deeprec_tpu.training import metrics as M
from deeprec_tpu.training.trainer import (
    Trainer,
    TrainState,
    stack_batches,
)


def _local_cfg(cfg, num_shards: int):
    assert cfg.capacity % num_shards == 0, (
        f"table {cfg.name}: capacity {cfg.capacity} not divisible by mesh size"
    )
    return dataclasses.replace(cfg, capacity=cfg.capacity // num_shards)


class ShardedTrainer(Trainer):
    """Drop-in Trainer over a device mesh: tables hash-sharded, batch split."""

    def __init__(
        self,
        model,
        sparse_opt,
        dense_opt: Optional[optax.GradientTransformation] = None,
        mesh: Optional[Mesh] = None,
        axis: str = DATA_AXIS,
        grad_averaging: bool = False,
        comm: str = "allgather",  # "a2a" (budgeted, SOK path) | "hier" (2-D)
        remat: bool = False,
        a2a_slack: float = 2.0,
        unique_budget=None,
        pipeline_mode: str = "off",
        pipeline_chunks: int = 4,
        placement: str = "uniform",
        placement_hot_budget: int = 64,
        replan: Optional["placement_lib.ReplanConfig"] = None,
        hier_group_factor: Optional[float] = None,
    ):
        from deeprec_tpu.parallel.costmodel import PlacementCostModel
        from deeprec_tpu.parallel.mesh import make_mesh, mesh_batch_axes

        self.mesh = mesh or make_mesh(axis=axis)
        # The axis spec every P()/collective in the step program uses: the
        # plain data axis of a 1-D mesh, or the (inter, intra) tuple of a
        # make_mesh_2d mesh — flat collectives over the tuple enumerate
        # devices in 1-D host-major rank order, so the allgather/a2a
        # programs (and hash ownership, and checkpoints) are identical
        # across mesh shapes. comm="hier" splits the exchange across the
        # two tiers instead (docs/multihost.md).
        self.axis = mesh_batch_axes(self.mesh)
        self.num_shards = self.mesh.devices.size
        names = tuple(self.mesh.axis_names)
        self.inter_size = self.mesh.shape[names[0]] if len(names) == 2 else None
        self.intra_size = self.mesh.shape[names[1]] if len(names) == 2 else None
        self.hier_group_factor = hier_group_factor
        if comm == "hier" and len(names) != 2:
            raise ValueError(
                "comm='hier' needs a 2-D mesh (make_mesh_2d); "
                f"got axes {names}"
            )
        # Skew-aware table placement (parallel/placement.py): "uniform"
        # keeps the legacy hash_shard routing; "plan" arms the
        # drift-driven replanner — maintain() runs maybe_replan() next to
        # update_budgets, which fires the cost-model placer only when the
        # live per-shard imbalance telemetry breaches the ReplanConfig
        # trigger (hysteresis + cooldown) AND the modeled gain amortizes
        # the modeled migration within the horizon. Plans always start
        # uniform; update_placement(force=True) also works under
        # "uniform" for one-shot manual placement.
        if placement not in ("uniform", "plan"):
            raise ValueError(
                f"placement must be 'uniform' or 'plan', got {placement!r}"
            )
        self.placement = placement
        self.placement_hot_budget = int(placement_hot_budget)
        self.replan_config = replan or placement_lib.ReplanConfig()
        self._drift = placement_lib.DriftDetector(self.replan_config)
        # Learned cost model (parallel/costmodel.py): trained from this
        # trainer's own (plan, measured per-shard bytes) windows, used by
        # build_plans to rank analytically-tied rotations; bit-identical
        # fallback until trained.
        self.cost_model = PlacementCostModel()
        self._plans: Dict[str, "BundlePlan"] = {}
        self.last_placement: Optional[Dict] = None
        self._window_reset_step = 0
        # (bundle, member) -> (step, sorted keys, freqs) at the last
        # placer run — the windowed-arrivals baseline (_member_traffics).
        self._freq_snaps: Dict = {}
        self._replan_stats: Dict[str, object] = {
            "replans": 0, "forced_replans": 0, "migration_rows": 0,
            "migration_bytes": 0.0, "deferred": 0,
            "last_gain_bytes_per_step": None,
        }
        super().__init__(model, sparse_opt, dense_opt, grad_averaging, remat,
                         unique_budget=unique_budget,
                         pipeline_mode=pipeline_mode,
                         pipeline_chunks=pipeline_chunks)
        # Re-point bundles at per-shard capacities + collective wrappers.
        # pipeline_mode="chunked" splits each table's value/grad exchanges
        # into pipeline_chunks column chunks (ShardedTable.exchange_chunks)
        # on EVERY train path (single-step and K-step scan) — bitwise
        # identical arithmetic, overlappable wire. "nested" (the 2-D-mesh
        # lookahead) keeps the chunked exchanges too: the inter-tier hop
        # of chunk k overlaps the intra-tier hop of chunk k+1.
        chunks = pipeline_chunks if pipeline_mode in ("chunked", "nested") else 1
        for bname, b in self.bundles.items():
            b.table = EmbeddingTable(_local_cfg(b.table.cfg, self.num_shards))
        self.sharded = {
            bname: ShardedTable(b.table, self.num_shards, self.axis,
                                comm=comm, a2a_slack=a2a_slack,
                                exchange_chunks=chunks,
                                intra=self.intra_size, inter=self.inter_size,
                                hier_group_factor=hier_group_factor)
            for bname, b in self.bundles.items()
        }

    def _make_jits(self):
        # Called by Trainer.__init__ (before self.sharded exists — jit
        # wrapping is lazy) and by update_budgets on a budget change.
        self._train_step = jax.jit(self._sharded_step, donate_argnums=0)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps
        self._train_step_accum = jax.jit(self._sharded_accum, donate_argnums=0)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps
        self._train_steps = jax.jit(self._sharded_steps, donate_argnums=0)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps
        self._eval_step = jax.jit(self._sharded_eval)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps

    def _stage_put(self, batch):
        # auto-stage (Trainer.stage) places batches with mesh sharding so
        # the staged transfer already lands split across devices
        from deeprec_tpu.parallel.mesh import shard_batch

        return shard_batch(self.mesh, batch, axis=self.axis)

    # ------------------------------------------------------------------ init

    def init(self, seed: int = 0) -> TrainState:
        from deeprec_tpu.parallel.mesh import put_global, put_tiled_global

        key = jax.random.PRNGKey(seed)
        dense = self.model.init(key)
        N = self.num_shards
        tables = {}
        for bname, b in self.bundles.items():
            local = ensure_slots(b.table, b.table.create(), self.sparse_opt)
            # layout: [T?, N, C_local, ...] — shard axis right before
            # capacity. The per-shard template tiles identically along the
            # lead axes; put_tiled_global never materializes the pod-scale
            # global value on one host.
            if b.stacked:
                lead = (len(b.features), N)
                spec = P(None, self.axis)
            else:
                lead = (N,)
                spec = P(self.axis)
            sh = NamedSharding(self.mesh, spec)
            tables[bname] = jax.tree.map(
                lambda a, lead=lead, s=sh: put_tiled_global(a, lead, s), local
            )
        repl = NamedSharding(self.mesh, P())
        put_repl = lambda t: jax.tree.map(lambda a: put_global(a, repl), t)
        return TrainState(
            step=put_global(jnp.zeros((), jnp.int32), repl),
            tables=tables,
            dense=put_repl(dense),
            opt_state=put_repl(self.dense_opt.init(dense)),
        )

    # -------------------------------------------------------------- internals

    def _table_spec(self, bname):
        b = self.bundles[bname]
        return P(None, self.axis) if b.stacked else P(self.axis)

    def _specs_for(self, state: TrainState, batch):
        ax = self.axis
        state_spec = TrainState(
            step=P(),
            tables={
                bname: jax.tree.map(lambda _: self._table_spec(bname), ts)
                for bname, ts in state.tables.items()
            },
            dense=jax.tree.map(lambda _: P(), state.dense),
            opt_state=jax.tree.map(lambda _: P(), state.opt_state),
        )
        batch_spec = jax.tree.map(lambda _: P(ax), batch)
        return state_spec, batch_spec

    def _squeeze(self, bname, ts):
        ax = 1 if self.bundles[bname].stacked else 0
        return jax.tree.map(lambda a: jnp.squeeze(a, axis=ax), ts)

    def _unsqueeze(self, bname, ts):
        ax = 1 if self.bundles[bname].stacked else 0
        return jax.tree.map(lambda a: jnp.expand_dims(a, axis=ax), ts)

    def _evict_bundle(self, b, ts, step):
        # leading dims: [T?, N, C]; evict each shard's local table
        fills = self._slot_fills(b)
        fn = lambda s: b.table.evict(s, step, slot_fills=fills)
        fn = jax.vmap(fn)  # over shards
        if b.stacked:
            fn = jax.vmap(fn)  # over grouped tables
        return fn(ts)

    # Per-bundle primitives: the only thing that differs from the base
    # Trainer is that lookup/apply go through the collective ShardedTable.
    # The unique budget resolves on the LOCAL batch — dedup-at-budget runs
    # before the exchange, so the a2a payload / allgather return shrink by
    # the same U/N factor as the compute.
    def _budget_capacity(self, b):
        # The bundle's cfg is the PER-SHARD capacity; local-batch uniques
        # are bounded by the global table (they hash across all shards).
        return b.table.cfg.capacity * self.num_shards

    def _lookup_one(self, b, state, ids, pad, salt, step, train, plan=None):
        U = self._budget_for_lookup(b, ids, train)
        return self.sharded[b.name].lookup_unique(
            state, ids, step=step, train=train, pad_value=pad, salt=salt,
            unique_size=U, plan=plan,
        )

    def _apply_one(self, b, state, res, grad, step, lr):
        # Sync sharded hot path: traffic-diet opt-in (see Trainer._apply_one).
        return self.sharded[b.name].apply_gradients(
            state, self.sparse_opt, res, grad, step=step, lr=lr,
            grad_averaging=self.grad_averaging,
            reuse_rows=self._bundle_reuse_rows(b), stamp_meta=False,
        )

    # Split-phase primitives (Trainer._route_all/_resolve_all/_finish_all
    # drive these): the collective versions — route carries the id
    # exchange, finish the embedding exchange.
    def _route_one(self, b, ids, pad, train, plan=None):
        U = self._budget_for_lookup(b, ids, train)
        return self.sharded[b.name].route(
            ids, pad_value=pad, unique_size=U, plan=plan
        )

    def _resolve_one(self, b, state, route, salt, step, train):
        return self.sharded[b.name].resolve(
            state, route, step=step, train=train, salt=salt
        )

    def _finish_one(self, b, state, pending, train, keep_rows=True):
        return self.sharded[b.name].finish(
            state, pending, train=train, keep_rows=keep_rows
        )

    def _carry_specs(self):
        """Prefix spec trees for a PipelineCarry's lookahead halves
        (shard_map broadcasts a spec over a subtree): views/batch leaves
        shard the leading local axis; stacked bundles carry their table
        axis first. Used where a carry crosses the shard_map boundary —
        the async stale-by-one stage (parallel/async_stage.py); the exact
        pipelined scan keeps its carry inside one shard_map region."""
        ax = self.axis
        views_spec = P(ax)
        res_spec = {
            bname: P(None, ax) if b.stacked else P(ax)
            for bname, b in self.bundles.items()
        }
        batch_spec = P(ax)
        return views_spec, res_spec, batch_spec

    # --------------------------------------------------------- placement

    def _bundle_plan_leaves(self, b):
        """Active ShardPlan of this bundle as device constants for the
        route paths (stacked bundles: leading [T] member axis, mapped by
        the lookup vmap). Uniform plans return {} so the compiled program
        is identical to the pre-placement one. Plan changes rebuild the
        jit wrappers (update_placement) — the constants are baked into
        the traced program, exactly like the resolved unique budgets."""
        import numpy as np

        bp = self._plans.get(b.name)
        if bp is None or bp.is_uniform:
            return {}
        return bp.leaves(np.dtype(b.table.cfg.key_dtype), stacked=b.stacked)

    def _per_shard_stats(self, b, member_ts):
        """Owner-load breakdown per mesh position for dedup_stats: the
        counters ShardedTable.resolve accumulates, converted to modeled
        exchange bytes (ops/traffic.py) and their max/mean imbalance."""
        import numpy as np

        from deeprec_tpu.ops import traffic as T

        oa = np.asarray(jax.device_get(member_ts.owner_arrivals))
        ou = np.asarray(jax.device_get(member_ts.owner_unique))
        if oa.ndim != 1:
            return None
        cfg = b.table.cfg
        wire = 2 if cfg.exchange_dtype == "bfloat16" else 4
        rb = T.exchange_row_bytes(dim=cfg.dim, wire_bytes=wire)
        xb = [round(float(a) * rb, 1) for a in oa]
        return {
            "owner_unique": [int(x) for x in ou],
            "owner_arrivals": [int(x) for x in oa],
            "exchange_bytes": xb,
            "imbalance": round(T.shard_imbalance(xb), 4),
        }

    def _member_traffics(self, state, return_pulls: bool = False):
        """Placer inputs: one MemberTraffic per member table, weights
        modeled from the live freq counters (TableState.meta) — a key's
        arrivals/step is at most its occurrence rate and at most N (each
        source shard dedups before the exchange).

        Windowed weights: once a freq snapshot exists (stamped at every
        placer run, `_snapshot_freqs`), the arrival rate is the DELTA
        since the snapshot over the window's steps — so a replan chases
        the distribution the drift trigger actually fired on, not the
        lifetime average a rotated hot set would dilute for thousands of
        steps. First run (no snapshot) uses lifetime freq/steps.

        return_pulls=True additionally returns the raw (keys, freqs)
        host arrays per member so `_snapshot_freqs` can reuse them —
        these are the full table pulls, paid once per placer run."""
        import numpy as np

        from deeprec_tpu.embedding.table import empty_key
        from deeprec_tpu.ops import traffic as T

        N = self.num_shards
        steps = max(1, int(state.step))
        out = []
        pulls = {}
        for bname, b in self.bundles.items():
            cfg = b.table.cfg
            sent = empty_key(cfg)
            wire = 2 if cfg.exchange_dtype == "bfloat16" else 4
            row_bytes = T.exchange_row_bytes(dim=cfg.dim, wire_bytes=wire)
            ts = state.tables[bname]
            keys_np = np.asarray(jax.device_get(ts.keys))  # [T?, N, C]
            freq_np = np.asarray(jax.device_get(ts.freq))
            for m in (range(len(b.features)) if b.stacked else [0]):
                k = keys_np[m] if b.stacked else keys_np  # [N, C]
                fq = freq_np[m] if b.stacked else freq_np
                occ = k != sent
                k_live = k[occ]
                f_live = fq[occ].astype(np.float64)
                pulls[(bname, m)] = (k_live, f_live)
                snap = self._freq_snaps.get((bname, m))
                w_steps = steps
                # A snapshot taken at THIS step means an empty window —
                # no arrivals to weight by; fall back to lifetime rates
                # (back-to-back placer runs, e.g. a deferred evaluation
                # immediately re-run with a different horizon).
                if snap is not None and steps - snap[0] > 0:
                    snap_step, snap_keys, snap_freq = snap
                    w_steps = steps - snap_step
                    if snap_keys.size:
                        pos = np.searchsorted(snap_keys, k_live)
                        pos = np.clip(pos, 0, len(snap_keys) - 1)
                        hit = snap_keys[pos] == k_live
                        prev = np.where(hit, snap_freq[pos], 0.0)
                    else:
                        prev = np.zeros_like(f_live)
                    # eviction/row-reinit resets freq mid-window: clamp
                    f_live = np.maximum(f_live - prev, 0.0)
                out.append(placement_lib.MemberTraffic(
                    bundle=bname, member=m, keys=k_live,
                    weight=np.minimum(f_live / w_steps, float(N)),
                    row_bytes=row_bytes, sentinel=sent,
                ))
        if return_pulls:
            return out, pulls
        return out

    def _snapshot_freqs(self, step: int, pulls) -> None:
        """Stamp the per-key freq counters (sorted by key, host-side) so
        the NEXT placer run models arrivals over the window since this
        one — called once per update_placement, reusing the host arrays
        `_member_traffics(return_pulls=True)` already fetched (no second
        full-table device pull)."""
        import numpy as np

        for ref, (k_live, f_live) in pulls.items():
            order = np.argsort(k_live, kind="stable")
            self._freq_snaps[ref] = (
                int(step), k_live[order], f_live[order]
            )

    def update_placement(self, state, *, hot_budget=None,
                         min_gain: Optional[float] = None,
                         force: bool = False,
                         horizon_steps: Optional[int] = None):
        """The cost-model placer, end to end: estimate per-shard exchange
        load from the live freq/dedup counters + per-table dims
        (ops/traffic.py), greedily build a candidate ShardPlan per member
        (parallel/placement.py build_plans, learned-cost-model assisted
        once trained), and — when it models at least `min_gain`x less
        max/mean imbalance than the ACTIVE plan AND the modeled
        straggler-bytes gain amortizes the modeled migration bytes within
        `horizon_steps` (or force=True, which skips both bars) — migrate
        moved rows between shards and swap the plan at this step
        boundary. The old plan serves until the swap; migration moves
        rows verbatim (bit-identical per-key state) and a migration that
        cannot place every key aborts, keeping the old plan. Adoption
        rebuilds the jitted steps (plan constants resolve at trace time,
        the update_budgets stale-executable contract) and sets the
        per-destination a2a budget vector (`ShardedTable.plan_dest_hot`).

        Every run also feeds the learned cost model one observation per
        member: the ACTIVE plan's modeled per-shard bytes next to the
        window's measured per-shard bytes — the placer's own history is
        its training set.

        Returns (state, report) with a per-bundle report; the global
        model + amortization numbers land on `self.last_placement`."""
        import numpy as np

        from jax.sharding import NamedSharding

        from deeprec_tpu.ops import traffic as T
        from deeprec_tpu.utils.hashing import hash_shard_np

        cfg = self.replan_config
        hot_budget = (
            self.placement_hot_budget if hot_budget is None else hot_budget
        )
        min_gain = cfg.min_gain if min_gain is None else min_gain
        horizon = cfg.horizon_steps if horizon_steps is None else horizon_steps
        step_now = int(state.step)
        snap_steps = {
            ref: step_now - snap[0] for ref, snap in self._freq_snaps.items()
        }
        members_info, pulls = self._member_traffics(state, return_pulls=True)
        current = {
            (m.bundle, m.member): self._plans[m.bundle].member(m.member)
            for m in members_info
            if m.bundle in self._plans
        }
        # Learned-cost-model observation: the ACTIVE plan's modeled
        # per-shard bytes/step vs what the window measured (the per-shard
        # owner counters, normalized by the window's steps). Recorded
        # BEFORE planning so even a deferred run teaches the model. The
        # two sides span different windows (modeled: since the last
        # placer run; measured: since the last counter reset), so pairs
        # are recorded only when the windows roughly coincide — a
        # first-run LIFETIME modeled vector paired with one post-drift
        # measured window would teach a systematically wrong correction.
        # The calibration is over the TAIL load only: build_plans queries
        # the model with tail-only rotation candidates (hot keys are
        # assigned later, by LPT), so hot-routed keys are excluded from
        # the modeled X and their modeled contribution subtracted from
        # the measured y — training and prediction see the same feature
        # distribution.
        window_steps = max(1, step_now - self._window_reset_step)
        measured = self._measured_member_windows(state, window_steps)
        for m in members_info:
            ref = (m.bundle, m.member)
            if ref not in measured or len(m.keys) == 0:
                continue
            ss = snap_steps.get(ref)
            if ss is None or ss <= 0 or ss > 2 * window_steps:
                continue  # no/empty/over-long modeled window: skip
            plan = current.get(ref)
            owner = (
                plan.owner_np(m.keys) if plan is not None
                else hash_shard_np(m.keys, self.num_shards)
            )
            load = m.weight * m.row_bytes
            hot_mask = (
                np.isin(m.keys, np.asarray(plan.hot_keys, m.keys.dtype))
                if plan is not None and plan.hot_keys else
                np.zeros(len(m.keys), bool)
            )
            modeled_tail = np.bincount(
                owner[~hot_mask], weights=load[~hot_mask],
                minlength=self.num_shards,
            )
            modeled_hot = np.bincount(
                owner[hot_mask], weights=load[hot_mask],
                minlength=self.num_shards,
            )
            self.cost_model.record_window(
                self.cost_model.member_stats(m), modeled_tail,
                np.maximum(measured[ref] - modeled_hot, 0.0),
            )
        # Next placer run models arrivals over the window starting HERE
        # (freq values survive migration verbatim, so the snapshot is
        # valid whether or not this run adopts; reuses the host arrays
        # already pulled above — no second full-table device pull).
        self._snapshot_freqs(step_now, pulls)
        # Multi-tier bundles keep uniform routing: their demoted rows live
        # in per-(bundle, shard) tier stores the migration cannot move —
        # re-routing a demoted key would strand its trained values/slots
        # on the old shard's store and re-insert it from the initializer.
        # Their (immovable) load still shapes the plan as a baseline the
        # placer packs around.
        pinned = {
            bname for bname, b in self.bundles.items()
            if b.table.cfg.ev.storage.storage_type.value in (
                "hbm_dram", "hbm_dram_ssd"
            )
        }
        plannable = [m for m in members_info if m.bundle not in pinned]
        fixed = [m for m in members_info if m.bundle in pinned]
        candidate, model_rep = placement_lib.build_plans(
            self.num_shards, plannable, hot_budget=hot_budget,
            base_loads=placement_lib.modeled_loads(self.num_shards, fixed),
            cost_model=self.cost_model,
        )
        loads_current = placement_lib.modeled_loads(
            self.num_shards, members_info, current
        )
        loads_candidate = placement_lib.modeled_loads(
            self.num_shards, members_info, candidate
        )
        imb_current = T.shard_imbalance(loads_current)
        imb_candidate = T.shard_imbalance(loads_candidate)
        # Amortization: straggler bytes/step saved vs the one-shot
        # migration bytes (exchange_row_bytes over the rows that would
        # move) — the replan must pay for itself within the horizon.
        moved_map = placement_lib.plan_moved_rows(
            plannable, current, candidate
        )
        row_bytes_by_ref = {
            (m.bundle, m.member): m.row_bytes for m in plannable
        }
        mig_bytes = sum(
            T.migration_bytes(n, row_bytes=row_bytes_by_ref[ref])
            for ref, n in moved_map.items()
        )
        gain = T.replan_gain_bytes(loads_current, loads_candidate)
        import math

        self.last_placement = dict(
            model_rep,
            imbalance_current=round(imb_current, 4),
            imbalance_candidate=round(imb_candidate, 4),
            gain_bytes_per_step=round(gain, 1),
            migration_rows=int(sum(moved_map.values())),
            migration_bytes=round(float(mig_bytes), 1),
            horizon_steps=horizon,
            amortize_steps=(
                int(math.ceil(mig_bytes / gain)) if gain > 0 else None
            ),
        )
        self._replan_stats["last_gain_bytes_per_step"] = round(gain, 1)
        from deeprec_tpu.obs import metrics as obs_metrics

        if obs_metrics.metrics_enabled():
            obs_metrics.default_registry().gauge(
                "deeprec_placement_modeled_gain",
                "modeled straggler exchange bytes/step a candidate plan "
                "would save over the active plan",
            ).set(gain)
        imb_ok = imb_candidate * min_gain <= imb_current
        amortized = gain > 0 and gain * float(horizon) >= mig_bytes
        adopt = force or (imb_ok and amortized)
        report = {}
        if not adopt:
            reason = "min_gain" if not imb_ok else "amortization"
            self._replan_stats["deferred"] = (
                int(self._replan_stats.get("deferred", 0)) + 1
            )
            self._replan_stats["last_deferred_reason"] = reason
            return state, {
                bname: {"adopted": False, "deferred": reason,
                        "imbalance_current": imb_current,
                        "imbalance_candidate": imb_candidate,
                        "gain_bytes_per_step": round(gain, 1),
                        "migration_bytes": round(float(mig_bytes), 1)}
                for bname in self.bundles
            }

        tables = dict(state.tables)
        changed_any = False
        moved_rows, moved_bytes = 0, 0.0
        for bname, b in self.bundles.items():
            if bname in pinned:
                report[bname] = {"adopted": False, "skipped": "multi_tier"}
                continue
            mlist = list(range(len(b.features))) if b.stacked else [0]
            bp_new = BundlePlan(tuple(candidate[(bname, m)] for m in mlist))
            bp_old = self._plans.get(bname)
            rep = {"adopted": False, "moved": 0,
                   "offsets": [p.offset for p in bp_new.plans],
                   "hot_keys": sum(len(p.hot_keys) for p in bp_new.plans)}
            if bp_old == bp_new or (bp_old is None and bp_new.is_uniform):
                rep["adopted"] = bp_old is not None or not bp_new.is_uniform
                report[bname] = rep
                continue
            ts = state.tables[bname]
            lead = self._bundle_lead_dims(b)
            idxs = list(np.ndindex(*lead))
            members = [jax.tree.map(lambda a, i=i: a[i], ts) for i in idxs]
            fills = self._slot_fills(b)
            N = self.num_shards
            new_members, moved_total, fail = [], 0, ""
            for m in mlist:
                shard_states = members[m * N:(m + 1) * N]
                res, moved, fail = placement_lib.reshard_members(
                    b.table, shard_states, bp_new.member(m).owner_np,
                    slot_fills=fills,
                )
                if res is None:
                    break
                # Local-dedup telemetry describes the SOURCE side — it is
                # unaffected by where rows live, so the window's counters
                # survive the migration (owner counters stay reset: they
                # were measured under the old plan). insert_fails survives
                # too: maintain()'s growth check reads it AFTER this swap
                # in the same call, and a migration must not eat a pending
                # grow signal.
                res = [
                    r.replace(dedup_unique=o.dedup_unique,
                              dedup_ids=o.dedup_ids,
                              dedup_overflow=o.dedup_overflow,
                              insert_fails=o.insert_fails,
                              a2a_overflow=o.a2a_overflow)
                    for r, o in zip(res, shard_states)
                ]
                new_members.extend(res)
                moved_total += moved
            if len(new_members) != len(members):
                rep["migrate_failed"] = fail or "reshard aborted"
                report[bname] = rep
                continue
            tables[bname] = jax.device_put(
                self._restack(new_members, lead),
                NamedSharding(self.mesh, self._table_spec(bname)),
            )
            self._plans[bname] = bp_new
            # Per-destination a2a budget vector: each destination's
            # bucket pays the hot-key arrivals THIS plan routes to it
            # (elementwise-max across vmapped members — they share the
            # bucket) on top of the tail share, which shrinks by the
            # keys every member routes explicitly
            # (ShardedTable._a2a_budget / ops/traffic.py
            # a2a_dest_budgets; static, baked at the jit rebuild).
            dest_hot = bp_new.dest_hot_counts()
            if dest_hot.any():
                self.sharded[bname].plan_dest_hot = dest_hot
                self.sharded[bname].plan_hot_count = bp_new.hot_count_min()
            else:
                self.sharded[bname].plan_dest_hot = None
                self.sharded[bname].plan_hot_count = 0
            # (bname, 0) is always in the dict: every non-pinned
            # bundle's members are in `plannable`, which populated it.
            moved_bytes += T.migration_bytes(
                moved_total, row_bytes=row_bytes_by_ref[(bname, 0)],
            )
            moved_rows += moved_total
            rep.update(adopted=True, moved=moved_total)
            report[bname] = rep
            changed_any = True
        if changed_any:
            self._make_jits()
            self._replan_stats["replans"] = (
                int(self._replan_stats["replans"]) + 1
            )
            if force:
                self._replan_stats["forced_replans"] = (
                    int(self._replan_stats["forced_replans"]) + 1
                )
            self._replan_stats["migration_rows"] = (
                int(self._replan_stats["migration_rows"]) + moved_rows
            )
            self._replan_stats["migration_bytes"] = round(
                float(self._replan_stats["migration_bytes"]) + moved_bytes, 1
            )
            if obs_metrics.metrics_enabled():
                reg = obs_metrics.default_registry()
                reg.counter(
                    "deeprec_placement_replans",
                    "adopted placement replans",
                    {"trigger": "forced" if force else "auto"},
                ).inc(1)
                reg.counter(
                    "deeprec_placement_migration_bytes",
                    "modeled bytes of rows migrated at plan adoptions",
                ).inc(moved_bytes)
        return (
            TrainState(step=state.step, tables=tables, dense=state.dense,
                       opt_state=state.opt_state),
            report,
        )

    def _measured_member_windows(self, state, window_steps: int):
        """(bundle, member) -> measured per-shard exchange bytes/STEP of
        the current counter window — the learned cost model's training
        targets (same unit as the analytic load model). Members whose
        window saw no arrivals are skipped."""
        import numpy as np

        out = {}
        for bname, b in self.bundles.items():
            ts = state.tables[bname]
            for m in (range(len(b.features)) if b.stacked else [0]):
                member_ts = (
                    jax.tree.map(lambda a, m=m: a[m], ts) if b.stacked
                    else ts
                )
                ps = self._per_shard_stats(b, member_ts)
                if not ps or sum(ps["owner_arrivals"]) == 0:
                    continue
                out[(bname, m)] = (
                    np.asarray(ps["exchange_bytes"], np.float64)
                    / max(1, int(window_steps))
                )
        return out

    def update_budgets(self, state, **kw):
        # The owner-load counters reset here; remember where the window
        # started so the replanner can normalize measured bytes to
        # bytes/step (the cost model's unit).
        state, rep = super().update_budgets(state, **kw)
        self._window_reset_step = int(state.step)
        return state, rep

    def maybe_replan(self, state):
        """The drift-driven replan trigger (maintain() runs this BEFORE
        update_budgets when placement="plan"): publish the window's
        per-shard telemetry into the obs plane, read the windowed
        imbalance level + its ring-buffer slope back
        (obs/metrics.py window queries — the PR 11 consumer contract),
        and run the placer only when the DriftDetector's hysteresis/
        cooldown gate fires. The placer itself then applies the
        min_gain + migration-amortization bars — so the system replans
        exactly when drift is real AND the move pays for itself."""
        if self.placement != "plan":
            return state, {}
        from deeprec_tpu.obs import metrics as obs_metrics

        cfg = self.replan_config
        stats = self.dedup_stats(state)  # device_get + gauge publish
        tables_ps = {
            t: d["per_shard"] for t, d in stats.items()
            if isinstance(d, dict) and d.get("per_shard")
        }
        level = max(
            (ps["imbalance"] for ps in tables_ps.values()), default=1.0
        )
        slope = None
        if obs_metrics.metrics_enabled():
            reg = obs_metrics.default_registry()
            slopes = [
                reg.window(
                    "deeprec_shard_imbalance", {"table": t},
                    cfg.window_secs,
                ).get("slope_per_sec")
                for t in tables_ps
            ]
            slopes = [s for s in slopes if s is not None]
            slope = max(slopes) if slopes else None
        fired = self._drift.observe(level, slope)
        report = {"drift": dict(self._drift.last)}
        if not fired:
            return state, report
        state, placer_rep = self.update_placement(state)
        if any(
            r.get("adopted") for r in placer_rep.values()
            if isinstance(r, dict)
        ):
            self._drift.adopted()
        else:
            self._drift.deferred()
        report.update(placer_rep)
        return state, report

    def placement_stats(self):
        """Replanner telemetry (surfaced as
        dedup_stats()['__placement__'] — dunder key, so a real table
        named 'placement' cannot collide): adoption/migration counters,
        the last drift observation and the learned cost model's
        training state."""
        out = dict(self._replan_stats)
        out["cost_model"] = self.cost_model.info()
        if self._drift.last:
            out["drift"] = dict(self._drift.last)
        return out

    def dedup_stats(self, state):
        out = super().dedup_stats(state)
        if self.placement == "plan":
            # Added AFTER the per-table gauge publication (super() has
            # already run _publish_dedup_obs); per-table consumers use
            # .get("per_shard") and skip this record naturally. Dunder
            # key: a real table named "placement" must not collide.
            out["__placement__"] = self.placement_stats()
        return out

    def restore_owner(self, bname: str, member, keys):
        """Owner shard of `keys` under the ACTIVE plan — the checkpoint
        restore router (training/checkpoint.py) calls this instead of the
        bare hash so a checkpoint saved under plan A restores correctly
        into a trainer running plan B."""
        import numpy as np

        from deeprec_tpu.utils.hashing import hash_shard_np

        bp = self._plans.get(bname)
        if bp is None:
            return hash_shard_np(np.asarray(keys), self.num_shards)
        return bp.member(member).owner_np(keys)

    def routing_fingerprint(self, bname: str) -> str:
        """Stable digest of this bundle's ACTIVE routing. Recorded in the
        checkpoint manifest at save time and compared at restore: a
        shard's saved CBF sketch describes the residents its save-time
        routing put there, so the per-shard exact-sketch reuse is only
        valid when save and restore route identically — rows themselves
        re-route freely (restore_owner), only the sketches fall back to
        the rebuild-from-rows path on a mismatch."""
        bp = self._plans.get(bname)
        if bp is None or bp.is_uniform:
            return "uniform"
        import hashlib

        canon = "|".join(
            f"{p.num_shards}:{p.offset}:"
            f"{','.join(map(str, p.hot_keys))}:"
            f"{','.join(map(str, p.hot_owners))}"
            for p in bp.plans
        )
        return hashlib.sha1(canon.encode()).hexdigest()[:16]

    # --------------------------------------------- capacity management

    def _bundle_lead_dims(self, b):
        # [T?, N, C_local]: members iterate grouped tables × shards.
        T = (len(b.features),) if b.stacked else ()
        return T + (self.num_shards,)

    def _set_bundle_capacity(self, b, new_c):
        super()._set_bundle_capacity(b, new_c)
        # Re-point the collective wrapper at the grown local table. The
        # per-dest a2a budget vector carries over: the adopted plan still
        # concentrates its hot keys regardless of capacity, and dropping
        # it here would re-expose the overflow-degraded hot ids the
        # budget exists to prevent (growth and adoption can land in the
        # SAME maintain call).
        old = self.sharded[b.name]
        self.sharded[b.name] = ShardedTable(
            b.table, old.num_shards, old.axis, comm=old.comm,
            a2a_slack=old.a2a_slack, exchange_chunks=old.exchange_chunks,
            intra=old.intra, inter=old.inter,
            hier_group_factor=old.hier_group_factor,
        )
        self.sharded[b.name].plan_dest_hot = old.plan_dest_hot
        self.sharded[b.name].plan_hot_count = old.plan_hot_count

    def maintain(self, state, **kw):
        # max_capacity is the GLOBAL cap; the base loop compares against
        # per-shard local capacities.
        if kw.get("max_capacity"):
            kw["max_capacity"] = max(1, kw["max_capacity"] // self.num_shards)
        state, report = super().maintain(state, **kw)
        # Growth changed per-shard shapes: restore the mesh sharding the
        # step functions expect (host-side stacking produced unsharded
        # arrays).
        from jax.sharding import NamedSharding

        tables = {}
        for bname, ts in state.tables.items():
            spec = self._table_spec(bname)
            tables[bname] = jax.device_put(
                ts, NamedSharding(self.mesh, spec)
            )
        return (
            TrainState(step=state.step, tables=tables, dense=state.dense,
                       opt_state=state.opt_state),
            report,
        )

    # ------------------------------------------------------------------ steps

    def _sharded_micro(self, tables, dense, batch, step, lr):
        """One (micro-)batch inside shard_map: lookups, fwd/bwd, sparse
        applies; returns tables, pmean'd dense grads (unapplied), metrics."""
        with jax.named_scope("phase_lookup_exchange"):
            tables, views, bundle_res = self._lookup_all(
                tables, batch, step, True
            )
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}

        def loss_fn(dense, embs):
            inputs = self._build_inputs(embs, views, batch)
            out = self.model.apply(dense, inputs, train=True)
            loss, out = self._loss_from_logits(out, batch)
            return loss, out

        with jax.named_scope("phase_dense_fwd_bwd"):
            (loss, out), (g_dense, g_embs) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True
            )(dense, embs)
        # Data-parallel dense grads: mean over replicas via ICI allreduce.
        g_dense = jax.lax.pmean(g_dense, self.axis)
        with jax.named_scope("phase_sparse_apply"):
            tables = self._apply_all(tables, bundle_res, g_embs, step, lr)

        mets = {"loss": jax.lax.pmean(loss, self.axis)}
        if not isinstance(out, dict):
            probs = jax.nn.sigmoid(out)
            mets["accuracy"] = jax.lax.pmean(
                M.accuracy(probs, batch["label"]), self.axis
            )
        else:
            mets["accuracy"] = jnp.zeros(())
        return tables, g_dense, mets

    def _sharded_body(self, state: TrainState, batch, lr):
        """One full train step on per-shard values (runs INSIDE shard_map):
        squeeze the shard axis off the tables, micro-step, dense update,
        re-wrap. Shared by the single-step path and the K-step scan."""
        step = state.step
        tables = {
            bname: self._squeeze(bname, ts)
            for bname, ts in state.tables.items()
        }
        tables, g_dense, mets = self._sharded_micro(
            tables, state.dense, batch, step, lr
        )
        updates, opt_state = self.dense_opt.update(
            g_dense, state.opt_state, state.dense
        )
        dense = optax.apply_updates(state.dense, updates)
        new_state = TrainState(
            step=step + 1,
            tables={
                bname: self._unsqueeze(bname, ts)
                for bname, ts in tables.items()
            },
            dense=dense,
            opt_state=opt_state,
        )
        return new_state, mets

    def _sharded_step(self, state: TrainState, batch, lr):
        state_spec, batch_spec = self._specs_for(state, batch)
        out_metric_spec = {"loss": P(), "accuracy": P()}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec, P()),
            out_specs=(state_spec, out_metric_spec),
            check_vma=False,
        )
        def run(state, batch, lr):
            return self._sharded_body(state, batch, lr)

        return run(state, batch, lr)

    def _sharded_steps(self, state: TrainState, batches, lr):
        """K-step device loop (Trainer._steps_impl mirror): one shard_map
        whose body scans `_sharded_body` over the K-stacked batch — the
        a2a/allgather exchange of every inner step stays inside the single
        compiled program, so K steps cost one host dispatch. Batch leaves
        are [K, B, ...] with the K axis unsharded and the batch axis split
        over the mesh (`shard_batch(..., stacked=True)`).

        pipeline_mode != "off" routes to the rotated scan
        (`_sharded_steps_pipelined`): same semantics, bit-exact, with the
        id exchange + owner probe of batch t+1 hoisted over batch t's
        dense compute."""
        if self.pipeline_mode != "off":
            return self._sharded_steps_pipelined(state, batches, lr)
        state_spec, _ = self._specs_for(state, {})
        batch_spec = jax.tree.map(lambda _: P(None, self.axis), batches)
        out_metric_spec = {"loss": P(), "accuracy": P()}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec, P()),
            out_specs=(state_spec, out_metric_spec),
            check_vma=False,
        )
        def run(state, batches, lr):
            def body(state, batch):
                return self._sharded_body(state, batch, lr)

            return jax.lax.scan(body, state, batches)

        return run(state, batches, lr)

    # -------------------------------------------- pipelined K-step scan

    def _sharded_pipe_prologue(self, state: TrainState, batch0):
        """Fill the pipeline inside shard_map: split-phase lookup of the
        window's first batch (same program as the sequential lookup)."""
        from deeprec_tpu.training.trainer import PipelineCarry

        tables = {
            bname: self._squeeze(bname, ts)
            for bname, ts in state.tables.items()
        }
        routes = self._route_all(batch0, True)
        tables, pending = self._resolve_all(tables, routes, state.step, True)
        views, res = self._finish_all(tables, pending, batch0, True)
        new_state = TrainState(
            step=state.step,
            tables={
                bname: self._unsqueeze(bname, ts)
                for bname, ts in tables.items()
            },
            dense=state.dense,
            opt_state=state.opt_state,
        )
        return PipelineCarry(inner=new_state, batch=batch0, views=views,
                             bundle_res=res)

    def _sharded_pipe_step(self, carry, batch_next, lr):
        """One pipelined sharded step on per-shard values (inside
        shard_map) — `Trainer._pipe_step` with the collective split
        phases and pmean'd dense grads/metrics:

          1. route(t+1): id dedup + id a2a/allgather + owner dedup —
             ids-only, issued before the dense compute so the async
             collective hides behind the matmuls. Under
             pipeline_mode="nested" with comm="hier" this is where the
             nesting lands: route contains BOTH tiers' id hops, so the
             expensive inter-tier exchange of t+1 (phase
             "hier_inter_ids") is issued a full dense fwd/bwd ahead and
             its DCN latency hides behind t's intra-host work AND
             matmuls;
          2. resolve(t+1): owner probe/insert + fused metadata + init —
             keys/meta only, commutes bit-exactly with apply(t);
          3. dense fwd/bwd on the carried lookup of batch t;
          4. grad exchange + sparse apply of batch t;
          5. finish(t+1): owner value gather + embedding exchange, AFTER
             the apply — batch t+1 sees post-apply tables, zero staleness.

        batch_next=None: window epilogue, only `.inner` of the returned
        carry is meaningful."""
        from deeprec_tpu.training.trainer import PipelineCarry

        state = carry.inner
        step = state.step
        tables = {
            bname: self._squeeze(bname, ts)
            for bname, ts in state.tables.items()
        }
        if batch_next is not None:
            with jax.named_scope("phase_route_next"):
                routes = self._route_all(batch_next, True)
                tables, pending = self._resolve_all(
                    tables, routes, step + 1, True
                )
        views = carry.views
        prev_batch = carry.batch
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}

        def loss_fn(dense, embs):
            inputs = self._build_inputs(embs, views, prev_batch)
            out = self.model.apply(dense, inputs, train=True)
            loss, out = self._loss_from_logits(out, prev_batch)
            return loss, out

        with jax.named_scope("phase_dense_fwd_bwd"):
            (loss, out), (g_dense, g_embs) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True
            )(state.dense, embs)
        g_dense = jax.lax.pmean(g_dense, self.axis)
        with jax.named_scope("phase_sparse_apply"):
            tables = self._apply_all(tables, carry.bundle_res, g_embs, step, lr)
        if batch_next is not None:
            with jax.named_scope("phase_finish_exchange"):
                views_n, res_n = self._finish_all(
                    tables, pending, batch_next, True
                )
        else:
            batch_next, views_n, res_n = prev_batch, views, carry.bundle_res
        updates, opt_state = self.dense_opt.update(
            g_dense, state.opt_state, state.dense
        )
        dense = optax.apply_updates(state.dense, updates)
        mets = {"loss": jax.lax.pmean(loss, self.axis)}
        if not isinstance(out, dict):
            probs = jax.nn.sigmoid(out)
            mets["accuracy"] = jax.lax.pmean(
                M.accuracy(probs, prev_batch["label"]), self.axis
            )
        else:
            mets["accuracy"] = jnp.zeros(())
        new_state = TrainState(
            step=step + 1,
            tables={
                bname: self._unsqueeze(bname, ts)
                for bname, ts in tables.items()
            },
            dense=dense,
            opt_state=opt_state,
        )
        return PipelineCarry(
            inner=new_state, batch=batch_next, views=views_n,
            bundle_res=res_n,
        ), mets

    def _sharded_steps_pipelined(self, state: TrainState, batches, lr):
        """The rotated K-step scan: prologue lookup of batch 0, a scan
        whose carry threads the one-batch lookahead (PipelineCarry — it
        never crosses the shard_map boundary, so it needs no specs), and a
        peeled epilogue for the last batch (which has nothing to
        prefetch; peeling keeps the final table state bit-identical — a
        masked dummy resolve would insert phantom keys)."""
        state_spec, _ = self._specs_for(state, {})
        batch_spec = jax.tree.map(lambda _: P(None, self.axis), batches)
        out_metric_spec = {"loss": P(), "accuracy": P()}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec, P()),
            out_specs=(state_spec, out_metric_spec),
            check_vma=False,
        )
        def run(state, batches, lr):
            batch0 = jax.tree.map(lambda x: x[0], batches)
            rest = jax.tree.map(lambda x: x[1:], batches)
            carry = self._sharded_pipe_prologue(state, batch0)

            def body(carry, batch_next):
                return self._sharded_pipe_step(carry, batch_next, lr)

            carry, mets = jax.lax.scan(body, carry, rest)
            carry, tail = self._sharded_pipe_step(carry, None, lr)
            mets = jax.tree.map(
                lambda a, b: jnp.concatenate([a, b[None]]), mets, tail
            )
            return carry.inner, mets

        return run(state, batches, lr)

    def _sharded_accum(self, state: TrainState, batch, lr):
        """Micro-batched sharded step: batch leaves [A, B_local*N, ...] — the
        accumulation axis is unsharded, the batch axis splits across the
        mesh; lax.scan over micro-batches inside the shard_map."""
        state_spec, _ = self._specs_for(state, {})
        batch_spec = jax.tree.map(lambda _: P(None, self.axis), batch)
        out_metric_spec = {"loss": P(), "accuracy": P()}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec, P()),
            out_specs=(state_spec, out_metric_spec),
            check_vma=False,
        )
        def run(state, batch, lr):
            step = state.step
            A = next(iter(batch.values())).shape[0]
            tables0 = {
                bname: self._squeeze(bname, ts)
                for bname, ts in state.tables.items()
            }

            def micro(carry, mb):
                tables, g_acc = carry
                tables, g_dense, mets = self._sharded_micro(
                    tables, state.dense, mb, step, lr
                )
                return (tables, jax.tree.map(jnp.add, g_acc, g_dense)), mets

            g0 = jax.tree.map(jnp.zeros_like, state.dense)
            (tables, g_acc), mets = jax.lax.scan(micro, (tables0, g0), batch)
            g_mean = jax.tree.map(lambda g: g / jnp.float32(A), g_acc)
            updates, opt_state = self.dense_opt.update(
                g_mean, state.opt_state, state.dense
            )
            dense = optax.apply_updates(state.dense, updates)
            new_state = TrainState(
                step=step + 1,
                tables={
                    bname: self._unsqueeze(bname, ts)
                    for bname, ts in tables.items()
                },
                dense=dense,
                opt_state=opt_state,
            )
            return new_state, jax.tree.map(jnp.mean, mets)

        return run(state, batch, lr)

    def train_steps(self, state: TrainState, batches, lr=None):
        """K steps per dispatch on the mesh. A list/tuple of batch dicts is
        stacked and placed with the K axis unsharded and the batch axis
        split (P(None, axis)); pass a pre-placed stacked pytree
        (`shard_batch(..., stacked=True)`) to skip the host round-trip."""
        if isinstance(batches, (list, tuple)):
            from deeprec_tpu.parallel.mesh import shard_batch

            batches = shard_batch(
                self.mesh, stack_batches(batches), axis=self.axis,
                stacked=True,
            )
        return super().train_steps(state, batches, lr)

    def _sharded_eval(self, state: TrainState, batch):
        state_spec, batch_spec = self._specs_for(state, batch)

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec),
            out_specs=(P(), P(self.axis)),
            check_vma=False,
        )
        def run(state, batch):
            tables = {
                bname: self._squeeze(bname, ts)
                for bname, ts in state.tables.items()
            }
            tables, views, _ = self._lookup_all(
                tables, batch, state.step, False
            )
            embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}
            inputs = self._build_inputs(embs, views, batch)
            out = self.model.apply(state.dense, inputs, train=False)
            loss, out = self._loss_from_logits(out, batch)
            probs = (
                {k: jax.nn.sigmoid(v) for k, v in out.items()}
                if isinstance(out, dict)
                else jax.nn.sigmoid(out)
            )
            return jax.lax.pmean(loss, self.axis), probs

        return run(state, batch)
