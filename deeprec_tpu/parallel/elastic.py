"""Elastic re-scaling: move live training state between topologies.

DeepRec's elastic training re-partitions PS-resident EVs through a gRPC
scaling protocol (core/protobuf/elastic_training.proto:38-76 —
IsReadyScaling polled by workers, ReadyToUpdate, UpdateServerDef with the
new cluster; served by contrib/elastic_grpc_server). This module carries
the same choreography onto a TPU pod, where the cluster is an SPMD mesh
rather than a PS set:

  * `reshard` — the state move: checkpoints restore by re-probing keys,
    so ANY saved state loads onto ANY mesh size or capacity.
  * `ElasticCoordinator` — the control plane, over a shared filesystem
    instead of gRPC (a TPU pod always has one for checkpoints). An
    autoscaler posts a scaling plan (`request_scale`); workers poll at
    step boundaries (`should_scale`, collectively agreed so every
    process decides at the SAME step); `ack_rescale` is the
    ReadyToUpdate barrier.
  * the launcher's `--elastic` supervisor (deeprec_tpu.launch) is the
    UpdateServerDef analog: jax pins the process set at
    jax.distributed.initialize, so changing the topology means the
    supervisor respawns the worker set at the new size and training
    resumes from the rescale checkpoint — mid-JOB, no operator action.

The file-coordinated WorkQueue (`data/work_queue.py`) re-balances the
data stream across the new worker set automatically because workers pull
items dynamically from the shared cursor.
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Optional, Tuple

from deeprec_tpu.training.checkpoint import CheckpointManager
from deeprec_tpu.training.trainer import TrainState, Trainer

#: exit code a worker uses to tell the supervisor "respawn me at the new
#: size" (any other nonzero exit aborts the job).
EXIT_RESCALE = 42


def factorize_mesh(n: int, prefer_intra: int) -> Tuple[int, int]:
    """Pick an ``(intra, inter)`` factorization for `n` surviving devices.

    After a rescale changes the device count, a 2-D hierarchical mesh
    (`make_mesh_2d`) must be rebuilt with ``intra * inter == n`` — a
    host-group leaving rarely preserves the old shape. Policy: keep the
    cheap tier as wide as possible without exceeding its old width
    (`prefer_intra`, typically the chips-per-host ICI domain, which the
    hardware bounds), i.e. the largest divisor of `n` that is
    ``<= prefer_intra`` with co-factor ``>= 2``. When no such divisor
    exists (prime counts, n < 4), degrade to 1-D — ``(n, 1)`` — rather
    than wedge: every n >= 1 gets a buildable mesh, and comm="hier"
    callers fall back to the flat exchange on the 1-D result.
    """
    if n < 1:
        raise ValueError(f"factorize_mesh: n must be >= 1, got {n}")
    for cand in range(min(int(prefer_intra), n // 2), 1, -1):
        if n % cand == 0:
            return cand, n // cand
    return n, 1  # 1-D degrade


def plan_mesh_after_rescale(n: int, old_mesh=None):
    """Build the mesh for `n` surviving devices, preserving the old
    mesh's hierarchy when one exists.

    1-D old mesh (or None) -> 1-D new mesh. 2-D old mesh -> the
    `factorize_mesh` shape seeded with the old intra width, degrading to
    1-D when `n` has no valid factorization (never raises for n >= 1 up
    to the available device count). Use on the respawn side of an
    EXIT_RESCALE cycle, before `reshard`/checkpoint restore — restore is
    mesh-shape independent, so the state loads regardless of which shape
    comes back.
    """
    from deeprec_tpu.parallel.mesh import make_mesh, make_mesh_2d

    names = tuple(old_mesh.axis_names) if old_mesh is not None else ()
    if len(names) != 2:
        return make_mesh(n)
    old_intra = int(old_mesh.shape[names[1]])
    intra, inter = factorize_mesh(n, old_intra)
    if inter == 1:
        return make_mesh(n)
    return make_mesh_2d(intra, inter)


def reshard(
    src_trainer: Trainer,
    src_state: TrainState,
    dst_trainer: Trainer,
    scratch_dir: Optional[str] = None,
) -> TrainState:
    """Re-partition `src_state` onto `dst_trainer`'s topology (different mesh
    size, different capacities, sharded<->single-device — anything whose
    model/features match).

    Goes through the checkpoint container (host RAM-disk scratch) so the
    exact same tested export/import path handles the move; keys re-probe into
    their new owners' shards.

    Multi-host: pass a SHARED scratch_dir — process 0 writes the files and
    every process must read them, so per-process tempdirs cannot work.
    """
    import jax

    if jax.process_count() > 1 and scratch_dir is None:
        raise ValueError(
            "multi-host reshard needs a shared scratch_dir (process 0 "
            "writes the checkpoint; every process reads it)"
        )
    d = scratch_dir or tempfile.mkdtemp(prefix="reshard_")
    src_ck = CheckpointManager(d, src_trainer, keep=1)
    _, path = src_ck.save(src_state)
    dst_state = CheckpointManager(d, dst_trainer, keep=1).restore()
    return dst_state


class ElasticCoordinator:
    """File-based scaling control plane (ElasticTrainingService analog).

    Plan file (`plan.json`): ``{"epoch": E, "target": N}`` — epoch
    increments per scaling event so a plan that already ran isn't re-run.
    Worker acks (`ack-E-P`): the ReadyToUpdate barrier — the supervisor
    respawns only after every worker of the outgoing generation acked.
    """

    def __init__(self, directory: str):
        self.dir = directory
        self._decided: Optional[Tuple[int, int]] = None  # (epoch, target)
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------- autoscaler

    def request_scale(self, target: int) -> int:
        """Post a scaling plan (the IsReadyScaling SCALING_UP/DOWN signal).
        Returns the new plan epoch."""
        epoch = self.plan()[0] + 1
        tmp = os.path.join(self.dir, f".plan.{epoch}.tmp")
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch, "target": int(target)}, f)
        os.replace(tmp, os.path.join(self.dir, "plan.json"))
        return epoch

    def plan(self) -> Tuple[int, Optional[int]]:
        """(epoch, target) of the current plan; (0, None) when none."""
        try:
            with open(os.path.join(self.dir, "plan.json")) as f:
                p = json.load(f)
            return int(p["epoch"]), int(p["target"])
        except (OSError, ValueError, KeyError):
            return 0, None

    # ---------------------------------------------------------- workers

    def should_scale(self) -> Optional[int]:
        """Poll at a step boundary. Returns the target process count when
        a new plan wants a DIFFERENT topology, else None.

        Collectively agreed: process 0's view of the plan file is
        broadcast to all processes, so every process decides at the same
        step even if the shared FS shows the file at different moments —
        the property the reference gets from a single coordinator serving
        IsReadyScaling (elastic_training.proto:38-47).
        """
        import jax

        done_epoch = int(os.environ.get("DEEPREC_ELASTIC_EPOCH", "0"))
        if jax.process_count() == 1:
            epoch, target = self.plan()
            if target is not None and epoch > done_epoch:
                self._decided = (epoch, target)
                return target
            return None
        from jax.experimental import multihost_utils
        import numpy as np

        if jax.process_index() == 0:
            epoch, target = self.plan()
            view = np.asarray(
                [epoch, target if target is not None else -1], np.int64
            )
        else:
            view = np.zeros(2, np.int64)
        view = multihost_utils.broadcast_one_to_all(view)
        epoch, target = int(view[0]), int(view[1])
        if target >= 0 and epoch > done_epoch:
            # Every process remembers the SAME (epoch, target) — acks must
            # reference this decision, not a re-read of plan.json, which a
            # racing autoscaler may already have replaced.
            self._decided = (epoch, target)
            return target
        return None

    def ack_rescale(self) -> None:
        """ReadyToUpdate: mark this process ready for the topology swap.
        Call after the rescale checkpoint is on disk, right before
        exiting with EXIT_RESCALE. Acks the plan epoch agreed in
        should_scale (the ack file body carries the agreed target, which
        the supervisor uses to size the next generation)."""
        import jax

        if self._decided is None:
            raise RuntimeError("ack_rescale without a should_scale decision")
        epoch, target = self._decided
        with open(
            os.path.join(
                self.dir, f"ack-{epoch}-{jax.process_index():05d}"
            ),
            "w",
        ) as f:
            f.write(str(target))

    def acked(self, epoch: int, n: int) -> bool:
        """Supervisor side: has every worker of the outgoing generation
        acked plan `epoch`?"""
        return all(
            os.path.exists(os.path.join(self.dir, f"ack-{epoch}-{p:05d}"))
            for p in range(n)
        )

    def wait_acked_after(
        self, after_epoch: int, n: int, timeout: float = 300.0
    ) -> Tuple[int, int]:
        """Supervisor side: wait until SOME epoch > after_epoch has all
        `n` worker acks; return (epoch, target). Scans rather than
        trusting the current plan.json — the workers may have agreed on an
        older plan than the latest posted one (a later plan will trigger
        the next generation's rescale)."""
        import glob as _glob
        import re

        deadline = time.time() + timeout
        pat = re.compile(r"ack-(\d+)-\d{5}$")
        while True:
            epochs = sorted({
                int(m.group(1))
                for p in _glob.glob(os.path.join(self.dir, "ack-*"))
                if (m := pat.search(p)) and int(m.group(1)) > after_epoch
            })
            for e in epochs:
                if self.acked(e, n):
                    with open(
                        os.path.join(self.dir, f"ack-{e}-00000")
                    ) as f:
                        return e, int(f.read().strip())
            if time.time() > deadline:
                raise TimeoutError(
                    f"elastic: {n} workers did not ack any plan after "
                    f"epoch {after_epoch} within {timeout}s"
                )
            time.sleep(0.05)

    def wait_acked(self, epoch: int, n: int, timeout: float = 300.0) -> None:
        deadline = time.time() + timeout
        while not self.acked(epoch, n):
            if time.time() > deadline:
                raise TimeoutError(
                    f"elastic: {n} workers did not ack plan {epoch} within "
                    f"{timeout}s"
                )
            time.sleep(0.05)
