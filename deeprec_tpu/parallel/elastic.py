"""Elastic re-scaling: move live training state between topologies.

DeepRec's elastic training re-partitions PS-resident EVs through a gRPC
scaling protocol (core/protobuf/elastic_training.proto, ElasticGrpcServer —
SURVEY.md §2.5). Here the equivalent is a structural property plus one
helper: checkpoints restore by re-probing keys, so ANY saved state loads
onto ANY mesh size or capacity; `reshard` packages that as a single in-memory
move for scale-up/scale-down events, and the file-coordinated WorkQueue
(`data/work_queue.py`) re-balances the data stream automatically because
workers pull items dynamically.
"""
from __future__ import annotations

import os
import tempfile
from typing import Optional

from deeprec_tpu.training.checkpoint import CheckpointManager
from deeprec_tpu.training.trainer import TrainState, Trainer


def reshard(
    src_trainer: Trainer,
    src_state: TrainState,
    dst_trainer: Trainer,
    scratch_dir: Optional[str] = None,
) -> TrainState:
    """Re-partition `src_state` onto `dst_trainer`'s topology (different mesh
    size, different capacities, sharded<->single-device — anything whose
    model/features match).

    Goes through the checkpoint container (host RAM-disk scratch) so the
    exact same tested export/import path handles the move; keys re-probe into
    their new owners' shards.

    Multi-host: pass a SHARED scratch_dir — process 0 writes the files and
    every process must read them, so per-process tempdirs cannot work.
    """
    import jax

    if jax.process_count() > 1 and scratch_dir is None:
        raise ValueError(
            "multi-host reshard needs a shared scratch_dir (process 0 "
            "writes the checkpoint; every process reads it)"
        )
    d = scratch_dir or tempfile.mkdtemp(prefix="reshard_")
    src_ck = CheckpointManager(d, src_trainer, keep=1)
    _, path = src_ck.save(src_state)
    dst_state = CheckpointManager(d, dst_trainer, keep=1).restore()
    return dst_state
