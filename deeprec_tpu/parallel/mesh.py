"""Device-mesh helpers.

The distributed design (SURVEY.md §2.5/§7): one mesh axis `data` carries both
data parallelism (batch split across all devices) and embedding model
parallelism (tables hash-sharded across the same devices) — exactly the
topology of DeepRec's CollectiveStrategy scope()/embedding_scope() over
HybridBackend/SOK (group_embedding_collective_strategy.py:29-108), with the
NVLink/NCCL exchanges replaced by XLA collectives over ICI.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = num_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (axis,))


def put_global(x, sharding: NamedSharding):
    """device_put that also works when the sharding spans processes: the
    host value (identical on every process) is placed shard-by-shard, each
    process contributing only its addressable pieces."""
    if jax.process_count() > 1:
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )
    return jax.device_put(x, sharding)


def put_tiled_global(local: "np.ndarray", lead: tuple, sharding: NamedSharding):
    """Place an array whose content is `local` tiled identically along
    `lead` leading axes (table-stack and shard axes) WITHOUT materializing
    the full global value anywhere: each process's callback broadcasts the
    shared per-shard template into just its addressable shards. This is
    what lets multi-host init create pod-scale tables that no single host
    could hold."""
    local = np.asarray(local)
    shape = tuple(lead) + local.shape

    def cb(idx):
        k = len(lead)
        tile = local[tuple(idx[k:])]
        lead_shape = tuple(
            len(range(*s.indices(d))) for s, d in zip(idx[:k], lead)
        )
        return np.broadcast_to(tile, lead_shape + tile.shape)

    if jax.process_count() > 1:
        return jax.make_array_from_callback(shape, sharding, cb)
    stacked = np.broadcast_to(local, shape)
    return jax.device_put(stacked, sharding)


def shard_batch(mesh: Mesh, batch: dict, axis: str = "data",
                stacked: bool = False) -> dict:
    """Place a host batch with batch-dim sharding over the mesh.

    stacked=True places a K-stacked batch pytree (leading [K, ...] axis,
    `training.stack_batches`) for `train_steps`: the K axis stays
    unsharded, the batch axis (dim 1) splits over the mesh.

    Multi-host aware: when the mesh spans processes (jax.distributed
    initialized), each process passes its LOCAL slice of the batch — sized
    B_global * local_devices / global_devices — and the global array is
    assembled across hosts (data stays put; no DCN transfer)."""
    sharding = NamedSharding(mesh, P(None, axis) if stacked else P(axis))
    if jax.process_count() > 1:
        return {
            k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
            for k, v in batch.items()
        }
    return {k: jax.device_put(v, sharding) for k, v in batch.items()}
