"""Device-mesh helpers.

The distributed design (SURVEY.md §2.5/§7): one mesh axis `data` carries both
data parallelism (batch split across all devices) and embedding model
parallelism (tables hash-sharded across the same devices) — exactly the
topology of DeepRec's CollectiveStrategy scope()/embedding_scope() over
HybridBackend/SOK (group_embedding_collective_strategy.py:29-108), with the
NVLink/NCCL exchanges replaced by XLA collectives over ICI.

Pod-scale meshes are 2-D (`make_mesh_2d`): a cheap `intra` axis over
same-host/ICI peers and an expensive `inter` axis across host groups (DCN).
Devices are laid out host-major so the flat rank ``g * intra + i`` of device
``(inter=g, intra=i)`` equals its 1-D `make_mesh` position — hash-shard
ownership, placement plans and checkpoints are therefore identical across
mesh shapes (see docs/multihost.md).
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names. shard_map callers and mesh builders must agree on
# these strings; drift fails only at trace time with an opaque unbound-axis
# error, so every in-repo user imports the constants instead of re-spelling
# the literal.
DATA_AXIS = "data"
INTRA_AXIS = "intra"  # cheap tier: same host group (ICI / NVLink)
INTER_AXIS = "inter"  # expensive tier: across host groups (DCN)

# An "axis spec" as accepted by collectives / PartitionSpec entries: the 1-D
# mesh uses the plain string, the 2-D mesh the (inter, intra) tuple —
# inter-major so the flattened device order matches the 1-D mesh.
AxisSpec = Union[str, Tuple[str, ...]]


def make_mesh(num_devices: Optional[int] = None, axis: str = DATA_AXIS) -> Mesh:
    devs = jax.devices()
    n = num_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (axis,))


def make_mesh_2d(intra: int, inter: Optional[int] = None) -> Mesh:
    """Two-tier mesh: axes ``(inter, intra)`` over ``inter * intra`` devices.

    `jax.devices()` enumerates devices host-major (all of process 0, then
    process 1, ...), so reshaping to ``(inter, intra)`` puts same-host /
    ICI-adjacent peers along the trailing `intra` axis — the cheap tier —
    and host-group boundaries along `inter`.  Flat rank of device
    ``(g, i)`` is ``g * intra + i``: identical to its `make_mesh` position,
    which keeps hash ownership and checkpoints mesh-shape independent.
    """
    devs = jax.devices()
    if inter is None:
        if len(devs) % intra:
            raise ValueError(
                f"intra={intra} does not divide device count {len(devs)}"
            )
        inter = len(devs) // intra
    n = intra * inter
    if n > len(devs):
        raise ValueError(
            f"mesh {inter}x{intra} needs {n} devices, have {len(devs)}"
        )
    grid = np.asarray(devs[:n]).reshape(inter, intra)
    return Mesh(grid, (INTER_AXIS, INTRA_AXIS))


def mesh_batch_axes(mesh: Mesh) -> AxisSpec:
    """The axis spec the batch dimension shards over: the single data axis
    of a 1-D mesh, or the (inter, intra) tuple of a 2-D mesh.  Tuple order
    is mesh-major (inter first) so flat collectives over it enumerate
    devices in 1-D rank order."""
    names = tuple(mesh.axis_names)
    return names[0] if len(names) == 1 else names


def axis_size(mesh: Mesh, axes: Optional[AxisSpec] = None) -> int:
    axes = mesh_batch_axes(mesh) if axes is None else axes
    if isinstance(axes, str):
        axes = (axes,)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n


def put_global(x, sharding: NamedSharding):
    """device_put that also works when the sharding spans processes: the
    host value (identical on every process) is placed shard-by-shard, each
    process contributing only its addressable pieces."""
    if jax.process_count() > 1:
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )
    return jax.device_put(x, sharding)


def put_tiled_global(local: "np.ndarray", lead: tuple, sharding: NamedSharding):
    """Place an array whose content is `local` tiled identically along
    `lead` leading axes (table-stack and shard axes) WITHOUT materializing
    the full global value anywhere: each process's callback broadcasts the
    shared per-shard template into just its addressable shards. This is
    what lets multi-host init create pod-scale tables that no single host
    could hold."""
    local = np.asarray(local)
    shape = tuple(lead) + local.shape

    def cb(idx):
        k = len(lead)
        tile = local[tuple(idx[k:])]
        lead_shape = tuple(
            len(range(*s.indices(d))) for s, d in zip(idx[:k], lead)
        )
        return np.broadcast_to(tile, lead_shape + tile.shape)

    if jax.process_count() > 1:
        return jax.make_array_from_callback(shape, sharding, cb)
    stacked = np.broadcast_to(local, shape)
    return jax.device_put(stacked, sharding)


def shard_batch(mesh: Mesh, batch: dict, axis: Optional[AxisSpec] = None,
                stacked: bool = False) -> dict:
    """Place a host batch with batch-dim sharding over the mesh.

    2-D-mesh aware: when `axis` is left None it is derived from the mesh —
    the single data axis of a 1-D mesh, or the ``(inter, intra)`` tuple of a
    `make_mesh_2d` mesh (batch splits over ALL devices either way, in the
    same flat order).

    stacked=True places a K-stacked batch pytree (leading [K, ...] axis,
    `training.stack_batches`) for `train_steps`: the K axis stays
    unsharded, the batch axis (dim 1) splits over the mesh.

    Multi-host aware: when the mesh spans processes (jax.distributed
    initialized), each process passes its LOCAL slice of the batch — sized
    B_global * local_devices / global_devices — and the global array is
    assembled across hosts (data stays put; no DCN transfer)."""
    if axis is None:
        axis = mesh_batch_axes(mesh)
    sharding = NamedSharding(mesh, P(None, axis) if stacked else P(axis))
    if jax.process_count() > 1:
        return {
            k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
            for k, v in batch.items()
        }
    return {k: jax.device_put(v, sharding) for k, v in batch.items()}
