"""Device-mesh helpers.

The distributed design (SURVEY.md §2.5/§7): one mesh axis `data` carries both
data parallelism (batch split across all devices) and embedding model
parallelism (tables hash-sharded across the same devices) — exactly the
topology of DeepRec's CollectiveStrategy scope()/embedding_scope() over
HybridBackend/SOK (group_embedding_collective_strategy.py:29-108), with the
NVLink/NCCL exchanges replaced by XLA collectives over ICI.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = num_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (axis,))


def shard_batch(mesh: Mesh, batch: dict, axis: str = "data") -> dict:
    """Place a host batch with batch-dim sharding over the mesh."""
    sharding = NamedSharding(mesh, P(axis))
    return {k: jax.device_put(v, sharding) for k, v in batch.items()}
