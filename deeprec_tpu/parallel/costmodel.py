"""Learned placement cost model — the DreamShard shape without the RL loop.

DreamShard (PAPERS.md 2210.02023) learns a placement cost model that
generalizes across table sets where a hand-built greedy model overfits its
tuning workload. This module is that idea scaled to the placer we actually
have: a small feature-based regressor (pure numpy — ridge via normal
equations, nothing to install, nothing stochastic) that learns to predict a
member table's MEASURED per-shard TAIL exchange bytes (hot-routed keys
excluded — build_plans queries the model with tail-only rotation
candidates, so training sees the same feature distribution) from the
analytic model's prediction plus per-table shape features:

    measured_tail_bytes[shard] ~ f(modeled_tail_bytes[shard], row_bytes,
                                   arrival mass, unique fraction,
                                   hot-mass concentration)

Training data is the placer's own history: every `update_placement` run
records, per member, the ACTIVE plan's modeled per-shard load next to the
window's measured per-shard exchange bytes (`dedup_stats()['per_shard']`,
normalized to bytes/step). The model is consulted only where the analytic
placer is ambiguous — rotation candidates whose analytic max-shard costs
tie (`build_plans(cost_model=)`) — and an UNTRAINED model changes nothing:
`trained` stays False until `min_rows` observations have accumulated, and
`build_plans` falls back to the analytic choice bit-identically
(tests/test_placement_v2.py pins both directions).

What the learned correction can know that the analytic model cannot: the
arrivals model `min(freq/steps, N)` systematically over-estimates keys
whose occurrences cluster on few source shards and under-estimates
dedup-budget interactions — per-table biases that are stable across
windows, exactly what a per-shard calibration absorbs.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, Optional

import numpy as np

# Feature vector per (member, shard) row — see _features():
#   0  modeled bytes/step the analytic model assigns this shard
#   1  log1p(row_bytes)          (table dim, via the wire-bytes weight)
#   2  log1p(mass * row_bytes)   (the member's total bytes/step)
#   3  unique fraction           (live keys per modeled arrival)
#   4  hot-mass concentration    (share of mass in the multi-source head)
N_FEATURES = 5


class PlacementCostModel:
    """Ridge regressor over per-(member, shard) load observations.

    Deterministic by construction: history is a bounded FIFO, fitting is
    closed-form normal equations, prediction is a dot product. The model
    never *proposes* placements — it only re-ranks candidates the
    analytic placer already considers equivalent, so a wrong model can at
    worst pick a different member of the analytic tie set."""

    def __init__(self, ridge: float = 1e-3, min_rows: int = 32,
                 max_rows: int = 4096):
        self.ridge = float(ridge)
        self.min_rows = int(min_rows)
        self._rows: deque = deque(maxlen=int(max_rows))
        self._coef: Optional[np.ndarray] = None  # [F + 1] incl. intercept
        self._mean: Optional[np.ndarray] = None
        self._scale: Optional[np.ndarray] = None
        self.observations = 0  # windows recorded (telemetry)

    # ------------------------------------------------------------ features

    @staticmethod
    def member_stats(member) -> Dict[str, float]:
        """Shard-independent features of one MemberTraffic: arrival mass,
        unique fraction and hot-mass concentration — the per-table shape
        the ISSUE's regressor conditions on."""
        w = np.asarray(member.weight, np.float64)
        mass = float(w.sum())
        n = int(len(member.keys))
        hot_mass = float(w[w > 1.0].sum()) / mass if mass > 0 else 0.0
        return {
            "row_bytes": float(member.row_bytes),
            "mass": mass,
            "unique_fraction": (n / mass) if mass > 0 else 0.0,
            "hot_mass": hot_mass,
        }

    @staticmethod
    def _features(stats: Dict[str, float], modeled: np.ndarray) -> np.ndarray:
        """[N, F] feature rows for one member's per-shard modeled loads."""
        modeled = np.asarray(modeled, np.float64)
        n = modeled.shape[0]
        out = np.empty((n, N_FEATURES), np.float64)
        out[:, 0] = modeled
        out[:, 1] = np.log1p(stats["row_bytes"])
        out[:, 2] = np.log1p(stats["mass"] * stats["row_bytes"])
        out[:, 3] = stats["unique_fraction"]
        out[:, 4] = stats["hot_mass"]
        return out

    # ------------------------------------------------------------ training

    def record_window(self, stats: Dict[str, float], modeled,
                      measured) -> None:
        """One observation window for one member: the analytic model's
        per-shard bytes/step under the ACTIVE plan next to the measured
        per-shard bytes/step the window actually produced. Windows with
        no traffic are skipped (an empty window teaches only noise)."""
        modeled = np.asarray(modeled, np.float64)
        measured = np.asarray(measured, np.float64)
        if modeled.shape != measured.shape:
            raise ValueError(
                f"modeled {modeled.shape} vs measured {measured.shape}"
            )
        if float(measured.sum()) <= 0.0:
            return
        X = self._features(stats, modeled)
        for i in range(X.shape[0]):
            self._rows.append((X[i], float(measured[i])))
        self.observations += 1
        self._fit()

    def _fit(self) -> None:
        if len(self._rows) < self.min_rows:
            return
        X = np.stack([r[0] for r in self._rows])
        y = np.asarray([r[1] for r in self._rows], np.float64)
        mean = X.mean(axis=0)
        scale = X.std(axis=0)
        scale[scale <= 0] = 1.0
        Xs = (X - mean) / scale
        A = np.concatenate([np.ones((Xs.shape[0], 1)), Xs], axis=1)
        reg = self.ridge * np.eye(A.shape[1])
        reg[0, 0] = 0.0  # never shrink the intercept
        try:
            coef = np.linalg.solve(A.T @ A + reg, A.T @ y)
        except np.linalg.LinAlgError:
            return  # keep the previous fit (or stay untrained)
        self._coef, self._mean, self._scale = coef, mean, scale

    @property
    def trained(self) -> bool:
        return self._coef is not None

    # ---------------------------------------------------------- prediction

    def predict_loads(self, stats: Dict[str, float],
                      modeled) -> np.ndarray:
        """Calibrated per-shard bytes/step for one member under a
        candidate assignment (`modeled` = the analytic per-shard vector).
        Predictions clamp at 0 — a calibration cannot un-send bytes."""
        if not self.trained:
            return np.asarray(modeled, np.float64)
        Xs = (self._features(stats, modeled) - self._mean) / self._scale
        pred = self._coef[0] + Xs @ self._coef[1:]
        return np.maximum(pred, 0.0)

    # ----------------------------------------------------------- telemetry

    def info(self) -> Dict[str, object]:
        return {
            "trained": self.trained,
            "rows": len(self._rows),
            "observations": self.observations,
        }
