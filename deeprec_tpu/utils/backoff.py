"""One capped-exponential-with-jitter backoff policy for every retry
loop in the repo.

Before this module the same policy was hand-rolled three times —
`TCPStreamReader.backoff_delay` (broker reconnects), the frontend's
`_Member.mark_down` (dead-backend routing backoff), and the serving
`_run_poll_loop` (delta-poll failures) — plus a fourth in the online
`Supervisor._restart`. Each re-derived the identical
``min(cap, base * 2^(k-1))`` shape with a ``[0.5, 1.5)`` jitter band and
each clamped the exponent differently, which is exactly the kind of
near-duplicate drift DRT lint rules can't see. The helpers here are
PURE (no sleeping, no clocks) so tests pin the whole policy without
waiting on it; callers own their RNG so jitter stays per-instance
deterministic where the call sites seeded it that way.
"""
from __future__ import annotations

import random
from typing import Optional

#: exponent clamp: 2**20 of any sane base is far past any cap, and an
#: unbounded attempt counter must never overflow the float exponent.
MAX_EXPONENT = 20


def backoff_delay(attempt: int, base: float, cap: float,
                  max_exponent: int = MAX_EXPONENT) -> float:
    """Capped exponential delay BEFORE jitter: the k-th consecutive
    failure (attempt=k, 1-based) waits ``base * 2**(k-1)``, never above
    ``cap``. ``attempt <= 1`` waits the base. Pure — pinned by unit
    tests without sleeping."""
    return min(cap, base * (2 ** max(0, min(attempt - 1, max_exponent))))


def jittered(delay: float, rng: random.Random,
             lo: float = 0.5, hi: float = 1.5) -> float:
    """Spread ``delay`` across ``[lo, hi) * delay`` so N clients hitting
    one dead peer don't re-probe in lockstep (the thundering-herd half
    of the policy; every call site uses the same band)."""
    return delay * (lo + (hi - lo) * rng.random())


def jittered_backoff(attempt: int, base: float, cap: float,
                     rng: random.Random,
                     max_exponent: int = MAX_EXPONENT,
                     lo: float = 0.5, hi: float = 1.5) -> float:
    """``jittered(backoff_delay(...))`` — the composition every retry
    loop actually sleeps on."""
    return jittered(backoff_delay(attempt, base, cap, max_exponent),
                    rng, lo, hi)


def seeded_rng(*identity, pid: Optional[int] = None) -> random.Random:
    """Per-instance jitter RNG seeded from an identity tuple
    (host, port, ...) so two members of one fleet never share a jitter
    stream. Stable within a process only — str hashing is salted per
    process, which is FINE for jitter (unlike routing: see the frontend's
    `_group_key`, which must use crc32 for exactly that reason). Pass
    ``pid`` to additionally decorrelate processes sharing an identity."""
    seed = hash(identity) & 0xFFFFFFFF
    if pid is not None:
        seed ^= pid & 0xFFFFFFFF
    return random.Random(seed)
