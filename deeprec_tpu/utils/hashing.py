"""Integer hashing utilities for hash-embedding tables.

TPU-native notes: everything here is vectorized uint32 arithmetic (VPU friendly,
no 64-bit emulation on the hot path). 64-bit keys are folded to 32 bits before
mixing; the table itself stores the full-width key for exact matching, so the
fold only affects probe-start distribution, never correctness.

Reference parity: DeepRec hashes keys inside its lockless CPU maps
(/root/reference/tensorflow/core/framework/embedding/cpu_hash_map_kv.h) and via
cuco on GPU (gpu_hash_table.cu.cc). Here hashing is explicit because the probe
sequence is computed in compiled XLA/Pallas code.
"""
from __future__ import annotations

import jax.numpy as jnp


def name_salt(name: str) -> int:
    """Stable per-name initializer salt. THE single definition — training
    (Bundle.salts) and serving (lookup_readonly) must agree on it, or grouped
    tables would serve different initializer vectors than training created."""
    import zlib

    return zlib.crc32(name.encode()) & 0x7FFFFFFF


def fold64(ids: jnp.ndarray) -> jnp.ndarray:
    """Fold integer ids of any width to uint32 for hashing."""
    if ids.dtype in (jnp.int64, jnp.uint64):
        lo = ids.astype(jnp.uint32)
        hi = (ids >> 32).astype(jnp.uint32)
        return lo ^ (hi * jnp.uint32(0x9E3779B9))
    return ids.astype(jnp.uint32)


def mix32(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 finalizer: a fast, well-distributed 32-bit mixer (VPU ops only)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def hash_to_bucket(ids: jnp.ndarray, num_buckets: int, salt: int = 0) -> jnp.ndarray:
    """Hash ids into [0, num_buckets). num_buckets must be a power of two."""
    assert num_buckets > 0 and (num_buckets & (num_buckets - 1)) == 0, (
        f"num_buckets must be a power of two, got {num_buckets}"
    )
    h = mix32(fold64(ids) ^ jnp.uint32(salt))
    return (h & jnp.uint32(num_buckets - 1)).astype(jnp.int32)


def hash_shard(ids: jnp.ndarray, num_shards: int) -> jnp.ndarray:
    """Owner shard of each id for model-parallel sharded tables (any num_shards)."""
    h = mix32(fold64(ids))
    # num_shards is usually a small power of two; modulo is fine either way.
    return (h % jnp.uint32(num_shards)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Host-side numpy mirrors. The placement subsystem (parallel/placement.py)
# and checkpoint re-shard routing compute key owners on the HOST at
# maintain/restore cadence; they must agree bit-for-bit with the compiled
# `hash_shard` above or a migrated key would be looked up on a shard where
# it doesn't live (and silently serve its initializer).

def fold64_np(ids):
    import numpy as np

    ids = np.asarray(ids)
    if ids.dtype in (np.int64, np.uint64):
        with np.errstate(over="ignore"):
            lo = ids.astype(np.uint32)
            hi = (ids >> 32).astype(np.uint32)
            return lo ^ (hi * np.uint32(0x9E3779B9))
    return ids.astype(np.uint32)


def mix32_np(x):
    import numpy as np

    with np.errstate(over="ignore"):
        x = np.asarray(x).astype(np.uint32)
        x = x ^ (x >> np.uint32(16))
        x = x * np.uint32(0x85EBCA6B)
        x = x ^ (x >> np.uint32(13))
        x = x * np.uint32(0xC2B2AE35)
        x = x ^ (x >> np.uint32(16))
        return x


def hash_shard_np(ids, num_shards: int):
    """Host mirror of `hash_shard` (bit-identical by construction).

    Mirrors the whole device path INCLUDING `jnp.asarray`'s 64->32 bit
    truncation when x64 is disabled (the default): device keys are the
    table's 32-bit key dtype, so 64-bit host ids must narrow the same way
    they would on the way in."""
    import numpy as np

    import jax

    ids = np.asarray(ids)
    if not jax.config.jax_enable_x64:
        if ids.dtype == np.int64:
            ids = ids.astype(np.int32)
        elif ids.dtype == np.uint64:
            ids = ids.astype(np.uint32)
    h = mix32_np(fold64_np(ids))
    return (h % np.uint32(num_shards)).astype(np.int32)


def stateless_uniform_from_ids(
    ids: jnp.ndarray, salt, dtype=jnp.float32
) -> jnp.ndarray:
    """Deterministic per-id uniform in [0, 1) — used by per-key initializers.

    Being a pure function of (id, salt) makes initialization reproducible
    across shards, restarts and table growth without threading PRNG state
    through the lookup path. `salt` may be a python int or a traced scalar
    (grouped tables pass a per-table salt through vmap).
    """
    bits = mix32(fold64(ids) ^ mix32(jnp.asarray(salt).astype(jnp.uint32)))
    # 24 high bits -> [0, 1) float
    return (bits >> 8).astype(dtype) * dtype(1.0 / (1 << 24))
