"""Integer hashing utilities for hash-embedding tables.

TPU-native notes: everything here is vectorized uint32 arithmetic (VPU friendly,
no 64-bit emulation on the hot path). 64-bit keys are folded to 32 bits before
mixing; the table itself stores the full-width key for exact matching, so the
fold only affects probe-start distribution, never correctness.

Reference parity: DeepRec hashes keys inside its lockless CPU maps
(/root/reference/tensorflow/core/framework/embedding/cpu_hash_map_kv.h) and via
cuco on GPU (gpu_hash_table.cu.cc). Here hashing is explicit because the probe
sequence is computed in compiled XLA/Pallas code.
"""
from __future__ import annotations

import jax.numpy as jnp


def name_salt(name: str) -> int:
    """Stable per-name initializer salt. THE single definition — training
    (Bundle.salts) and serving (lookup_readonly) must agree on it, or grouped
    tables would serve different initializer vectors than training created."""
    import zlib

    return zlib.crc32(name.encode()) & 0x7FFFFFFF


def fold64(ids: jnp.ndarray) -> jnp.ndarray:
    """Fold integer ids of any width to uint32 for hashing."""
    if ids.dtype in (jnp.int64, jnp.uint64):
        lo = ids.astype(jnp.uint32)
        hi = (ids >> 32).astype(jnp.uint32)
        return lo ^ (hi * jnp.uint32(0x9E3779B9))
    return ids.astype(jnp.uint32)


def mix32(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 finalizer: a fast, well-distributed 32-bit mixer (VPU ops only)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def hash_to_bucket(ids: jnp.ndarray, num_buckets: int, salt: int = 0) -> jnp.ndarray:
    """Hash ids into [0, num_buckets). num_buckets must be a power of two."""
    assert num_buckets > 0 and (num_buckets & (num_buckets - 1)) == 0, (
        f"num_buckets must be a power of two, got {num_buckets}"
    )
    h = mix32(fold64(ids) ^ jnp.uint32(salt))
    return (h & jnp.uint32(num_buckets - 1)).astype(jnp.int32)


def hash_shard(ids: jnp.ndarray, num_shards: int) -> jnp.ndarray:
    """Owner shard of each id for model-parallel sharded tables (any num_shards)."""
    h = mix32(fold64(ids))
    # num_shards is usually a small power of two; modulo is fine either way.
    return (h % jnp.uint32(num_shards)).astype(jnp.int32)


def stateless_uniform_from_ids(
    ids: jnp.ndarray, salt, dtype=jnp.float32
) -> jnp.ndarray:
    """Deterministic per-id uniform in [0, 1) — used by per-key initializers.

    Being a pure function of (id, salt) makes initialization reproducible
    across shards, restarts and table growth without threading PRNG state
    through the lookup path. `salt` may be a python int or a traced scalar
    (grouped tables pass a per-table salt through vmap).
    """
    bits = mix32(fold64(ids) ^ mix32(jnp.asarray(salt).astype(jnp.uint32)))
    # 24 high bits -> [0, 1) float
    return (bits >> 8).astype(dtype) * dtype(1.0 / (1 << 24))
