"""Shared ragged/rectangular padding — ONE vectorized implementation.

Three call sites used to carry parallel copies of "pad/trim bags to
[B, L]": the serving request parser (`serving/predictor.py::pad_ragged`),
the retrieval ingest coercion (`serving/retrieval.py::_coerce_item_col`),
and the reader-side multivalue packing. They are now all this module.

Semantics (the serving contract, pinned by
tests/test_serving_update.py::_legacy_ragged_pad):
  * each row pads with `pad_value` up to L and trims past L,
  * a scalar bag (non-list row) is a length-1 bag,
  * dtype is applied to the values, pad included.
"""
from __future__ import annotations

from itertools import chain
from typing import List

import numpy as np


def pad_ragged(rows: List, L: int, pad_value, dtype) -> np.ndarray:
    """Bulk pad/trim a ragged list-of-bags to [B, L]: one flatten, one
    index grid, one scatter — no per-row Python list building (the old
    `[(r + [pad] * (L - len(r)))[:L] for r in v]` walked every bag in
    the interpreter, which dominated parse time for long histories)."""
    B = len(rows)
    lens = np.fromiter(map(len, rows), np.intp, count=B)
    total = int(lens.sum())
    out = np.full((B, L), pad_value, dtype)
    if total == 0:
        return out
    flat = np.fromiter(chain.from_iterable(rows), dtype, count=total)
    starts = np.cumsum(lens) - lens
    col = np.arange(total) - np.repeat(starts, lens)
    keep = col < L
    row = np.repeat(np.arange(B, dtype=np.intp), lens)
    out[row[keep], col[keep]] = flat[keep]
    return out


def pad_rect(arr: np.ndarray, L: int, pad_value, dtype) -> np.ndarray:
    """Rectangular cousin of `pad_ragged`: coerce an already-rectangular
    [B] or [B, W] array to [B, L] — widen with `pad_value`, trim past L.
    The bulk-ingest path (retrieval upsert) where rows are not ragged."""
    arr = np.asarray(arr).astype(dtype)  # noqa: DRT002 — host coercion of reader/request rows, never a device array
    if arr.ndim == 1:
        arr = arr[:, None]
    if arr.shape[1] < L:
        pad = np.full((arr.shape[0], L - arr.shape[1]), pad_value, dtype)
        arr = np.concatenate([arr, pad], axis=1)
    return arr[:, :L]
