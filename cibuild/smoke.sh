#!/bin/bash
# Quick green: one representative test per subsystem, target < 5 min on a
# single core. The default `pytest -q` run (~10 min serial) covers
# everything but the slow-marked grid; DEEPREC_FULL_TESTS=1 runs that too.
set -e
cd "$(dirname "$0")/.."
exec python -m pytest -q -p no:cacheprovider \
  tests/test_table.py \
  tests/test_fused_lookup.py \
  tests/test_predict_pb.py \
  tests/test_kafka.py \
  tests/test_data.py::test_determinism_same_seed_same_results \
  tests/test_train_e2e.py::test_wdl_learns_synthetic_criteo \
  tests/test_sharded.py::test_sharded_matches_single_device \
  tests/test_a2a.py::test_a2a_matches_allgather_and_local \
  tests/test_checkpoint.py::test_full_save_restore_roundtrip \
  tests/test_multi_tier.py \
  tests/test_tier_paging.py::test_fold_loses_to_newer_device_row_bit_exact \
  tests/test_tier_paging.py::test_fold_inserts_missing_keys_ahead_of_lookup \
  tests/test_tier_paging.py::test_pump_killed_mid_gather_leaves_stores_consistent \
  tests/test_tier_paging.py::test_lookup_with_fallback_dedup_parity \
  tests/test_tier_paging.py::test_row_cache_never_crosses_a_sync_boundary_that_changed_the_row \
  tests/test_serving.py::test_http_server_end_to_end \
  tests/test_serving.py::test_protobuf_wire_end_to_end \
  tests/test_processor_cabi.py \
  tests/test_elastic_live.py::test_coordinator_plan_epoch_and_acks \
  tests/test_attention.py::test_flash_matches_reference \
  tests/test_feature_demos.py::test_kafka_streaming_demo \
  tests/test_ckpt_corruption.py::test_corruption_never_raises_into_serving_and_self_heals \
  tests/test_online_loop.py::test_poll_thread_survives_raising_poll_and_recovers \
  tests/test_analysis.py::test_repo_check_is_green \
  tests/test_analysis.py::test_trace_guard_catches_reintroduced_per_call_jit_lambda \
  tests/test_obs.py::test_disabled_tracing_is_zero_allocation \
  tests/test_obs_wiring.py::test_trace_id_spans_http_edge_to_backend_stages \
  tests/test_backoff.py \
  tests/test_fleet.py::test_ring_remap_fraction_on_join_at_most_2_over_n \
  tests/test_fleet.py::test_registry_stale_lease_eviction_and_readmission_race \
  tests/test_fleet.py::test_frontend_drain_excludes_new_assignments_zero_failures \
  tests/test_guard.py::test_step_flags_matrix \
  tests/test_guard.py::test_sentinel_is_bitexact_noop_when_untripped \
  tests/test_guard.py::test_canary_gate_rejects_nan_delta_serving_continues \
  tests/test_guard_stream.py::test_tcp_reader_skips_oversized_frame_and_counts \
  tests/test_guard_stream.py::test_line_parser_garbage_matrix \
  tests/test_input_pipeline.py::test_block_parse_garbage_matrix_parity \
  tests/test_input_pipeline.py::test_pipeline_bit_identical_to_serial_any_worker_count \
  tests/test_input_pipeline.py::test_pipeline_deterministic_under_slow_worker \
  tests/test_input_pipeline.py::test_pipeline_staged_ring_exactly_once_resume \
  tests/test_retrieval.py::test_tie_determinism_block_size_independent \
  tests/test_retrieval.py::test_delta_fold_targets_changed_items_and_zero_compiles \
  tests/test_retrieval_fleet.py::test_two_shard_merge_parity_and_kill_partial \
  tests/test_placement_v2.py::test_dest_budget_vector_uniform_parity_and_diet \
  tests/test_placement_v2.py::test_drift_detector_hysteresis_cooldown_and_projection \
  tests/test_placement_v2.py::test_cost_model_untrained_is_bit_identical \
  tests/test_placement_v2.py::test_zipf_rotation_off_is_stream_identical_and_on_is_deterministic \
  tests/test_placement_v2.py::test_amortization_defers_below_horizon_and_adopts_above \
  "$@"
