#!/usr/bin/env bash
# CI entry (the cibuild/*.sh analog): native build, test suite on the
# virtual 8-device CPU mesh, driver entry checks, CPU bench smoke.
#
# Test tiers (single-core box: compile time dominates):
#   cibuild/smoke.sh          — curated subset, quick green (~2.5 min)
#   pytest -q                 — everything but slow-marked (~10-15 min)
#   DEEPREC_FULL_TESTS=1 ...  — the full grid incl. multi-process launches
# This script runs the default tier; pass SMOKE=1 for the quick tier.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== native build =="
make -C deeprec_tpu/native

echo "== static analysis (fast fail: retrace/host-sync/layout/thread-safety lints, docs/analysis.md) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python -m deeprec_tpu.analysis --check

if [[ "${SMOKE:-0}" == "1" ]]; then
  echo "== tests (smoke tier) =="
  env PYTHONPATH= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      bash cibuild/smoke.sh
else
  echo "== tests (virtual 8-device CPU mesh) =="
  env PYTHONPATH= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python -m pytest tests/ -q
fi

echo "== driver entries =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== dedup engine microbench (CPU smoke: both paths compile) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python tools/bench_dedup.py --smoke

echo "== traffic-diet microbench (CPU smoke: diet + legacy-apply arms) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python tools/bench_lookup.py --traffic --smoke

echo "== fused sparse step (CPU smoke: interpret-mode parity + modeled HBM diet gate) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python tools/bench_lookup.py --fused-step \
    --smoke --dim 128 --out /tmp/deeprec_fused_smoke.json
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-fused /tmp/deeprec_fused_smoke.json

echo "== host input pipeline bench (CPU smoke: vectorized block parse vs serial line parser, N-worker stream parity, training-thread pop cost) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python tools/bench_input.py --smoke \
    --out /tmp/deeprec_input_smoke.json

echo "== input pipeline gate (block parse ≥2× serial, bit-identical batch stream at every worker count, zero training-thread regression) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-input /tmp/deeprec_input_smoke.json

echo "== checkpoint choreography microbench (CPU smoke: sync + async paths) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python tools/bench_ckpt.py --smoke

echo "== serving bench (CPU smoke: group dispatch + 2-process socket tier + int8 residency + grouped two-tower, delta updates mid-load, /v1/stats) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python tools/bench_serving.py --smoke \
    --out /tmp/deeprec_serving_smoke.json

echo "== fleet bench (CPU smoke: lease discovery, rolling restart of every backend via EXIT_RESCALE respawn, 2->4->2 autoscale, torn lease — zero failed requests) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python tools/bench_fleet.py --smoke \
    --out /tmp/deeprec_serving_smoke.json

echo "== serving scale-out / quantized residency / grouped / fleet gates (drift fails the smoke) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-serving /tmp/deeprec_serving_smoke.json

echo "== obs overhead gate, serving arm (telemetry plane ≤2% + /metrics parses) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-obs /tmp/deeprec_serving_smoke.json

echo "== compute-reuse gate (zipf arm ≥2× effective qps, hit-rate floor, bit-identity, publish dip+recovery, 0 steady compiles) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-reuse /tmp/deeprec_serving_smoke.json

echo "== retrieval bench (CPU smoke: 1M-item blocked top-k sweep, int8 + fp32 residency, recall vs exact scan, gather baseline, delta-fold freshness, trace guard) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python tools/bench_retrieval.py --smoke \
    --out /tmp/deeprec_retrieval_smoke.json

echo "== full-corpus retrieval gate (recall/speedup/freshness/residency/compile drift fails the smoke) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-retrieval /tmp/deeprec_retrieval_smoke.json

echo "== freshness bench (CPU smoke: online loop, trainer SIGKILL + supervised restart, zero failed requests) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python tools/bench_freshness.py --smoke

echo "== guard bench (CPU smoke: poison matrix — NaN/extreme/label-flip/replays + exploding-LR window; sentinel detects ≤1 dispatch, rollback+quarantine, canary gate, AUC floor, zero failed requests) =="
env PYTHONPATH= JAX_PLATFORMS=cpu python tools/bench_guard.py --smoke \
    --out /tmp/deeprec_guard_smoke.json

echo "== model-quality firewall gate (drift fails the smoke) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-guard /tmp/deeprec_guard_smoke.json

echo "== bench (CPU smoke; real numbers come from TPU) =="
env PYTHONPATH= JAX_PLATFORMS=cpu BENCH_FORCED=1 BENCH_SMOKE=1 \
    BENCH_PIPELINE=grid python bench.py --placement --mesh --tier-paging --smoke \
    | tee /tmp/deeprec_bench_smoke.out
tail -n 1 /tmp/deeprec_bench_smoke.out > /tmp/deeprec_bench_smoke.json

echo "== traffic model vs measured op counts (drift fails the smoke) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-traffic /tmp/deeprec_bench_smoke.json

echo "== in-step pipelining grid vs overlap model (regression fails the smoke) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-overlap /tmp/deeprec_bench_smoke.json

echo "== skew-aware placement vs uniform hash + drifting-skew replanning (imbalance/drift gates fail the smoke: auto replan, recovery, zero a2a overflow, per-dest budget diet) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-imbalance /tmp/deeprec_bench_smoke.json

echo "== pod-scale 2-D mesh gate (hier inter-tier wire diet vs flat a2a, bitwise loss parity, zero overflow/steady compiles, nested K-scan bound) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-hierarchy /tmp/deeprec_bench_smoke.json

echo "== overlapped tier paging gate (fresh-init loss ≥10× lower with paging on, 0 steady fold compiles, fold stall ≤ sync stall; step tol loose on single-core CI, --overlap-tol precedent) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-tier /tmp/deeprec_bench_smoke.json \
    --tier-step-tol 0.5

echo "== steady-state retrace gate (compiles inside timed windows fail the smoke) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-compiles /tmp/deeprec_bench_smoke.json

echo "== obs overhead gate, K-step scan arm (telemetry plane ≤2% + registry renders) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    python tools/roofline.py --assert-obs /tmp/deeprec_bench_smoke.json

echo "== bench (CPU smoke, budgets disabled: legacy dedup path compiles) =="
env PYTHONPATH= JAX_PLATFORMS=cpu BENCH_FORCED=1 BENCH_SMOKE=1 \
    BENCH_TIMED_STEPS=4 BENCH_K=4 BENCH_PIPELINE=off \
    python bench.py --unique-budget off
