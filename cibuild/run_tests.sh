#!/usr/bin/env bash
# CI entry (the cibuild/*.sh analog): native build, full test suite on the
# virtual 8-device CPU mesh, driver entry checks, CPU bench smoke.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== native build =="
make -C deeprec_tpu/native

echo "== tests (virtual 8-device CPU mesh) =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/ -q

echo "== driver entries =="
env PYTHONPATH= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== bench (CPU smoke; real numbers come from TPU) =="
env PYTHONPATH= JAX_PLATFORMS=cpu BENCH_FORCED=1 python bench.py
