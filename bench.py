"""Benchmark harness — prints ONE JSON line.

Metric: DLRM synthetic-Criteo training throughput (examples/sec) on the
available device, batch 2048, reference protocol mean(steps/sec) × batch
(modelzoo/benchmark/*/README.md). vs_baseline compares against the
reference's best published DLRM number: 188.11 global steps/sec × bs 2048 =
385,249 examples/sec on 1×A100-80G + 64-core Xeon
(docs/docs_en/Smart-Stage.md:182-190, see BASELINE.md).

Multi-step device loop: `--steps-per-dispatch K` (default 16) measures the
`Trainer.train_steps` path — K training steps per host dispatch via
`lax.scan` — and sweeps the K-curve over {1, 4, 16} ∩ [1, K] so the
dispatch-overhead amortization lands in the JSON (`k_curve`, with >= 3
timed repetitions and mean/min/max per K so single-core noise is
distinguishable from regression; see docs/perf.md). The headline `value`
is the requested K's best repetition; `steps_per_dispatch` records it.
`--smoke` (or BENCH_SMOKE=1, used by cibuild) shrinks the sweep and the
timed windows so CI completes quickly.

Unique budgets: `--unique-budget auto` (default) engages the hash dedup
engine (ops/dedup.py) — each table's unique fraction is measured during
pre-fill, folded into an EMA budget, and every downstream op of the lookup/
apply hot path is sized at the budget instead of the full flattened batch;
the JSON records the per-table `unique_fraction`/`dedup_overflow` under
"dedup" plus the run's "unique_budget" mode. `--unique-budget off` keeps the
legacy full-batch sort-unique for A/B comparison.

The TPU behind the axon tunnel is intermittent, so the harness probes with
retries across a window (BENCH_PROBE_ATTEMPTS × BENCH_PROBE_TIMEOUT, default
5 × 120s with 30s between failures, ~13 min worst case) and records probe
diagnostics in the JSON ("tpu": "ok" | "unreachable: <last error>") so a CPU
fallback is self-describing. The measured workload runs in a subprocess so a
tunnel that wedges mid-run degrades to the CPU number instead of hanging.
"""
import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_EXAMPLES_PER_SEC = 188.11 * 2048  # DLRM GPU SmartStage, BASELINE.md

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((256, 256));"
    "print((x @ x).sum(), jax.devices()[0].platform)"
)


def _probe_once(timeout: int):
    """One TPU liveness attempt in a subprocess. Returns (ok, diagnostic)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            timeout=timeout, capture_output=True, text=True,
        )
        if r.returncode == 0:
            # jax can silently init on CPU (JAX_PLATFORMS=cpu in the env, or
            # the tunnel's TPU runtime absent); that is NOT a live TPU.
            platform = (r.stdout or "").strip().split()[-1:]
            if platform == ["tpu"]:
                return True, "ok"
            # Deterministic verdict (this host resolves to cpu/gpu): not a
            # transient tunnel failure — tell the caller not to retry.
            return False, "notpu: probe ran on %s, not tpu" % (
                platform[0] if platform else "?")
        return False, "rc=%d: %s" % (
            r.returncode, _error_line(r.stderr or r.stdout or ""))
    except subprocess.TimeoutExpired:
        return False, "probe timed out after %ds" % timeout


def _error_line(text: str) -> str:
    """The most informative line of a crashed subprocess's output: prefer
    the exception line over jax's traceback-filtering boilerplate."""
    lines = [l.strip() for l in text.strip().splitlines() if l.strip()]
    for l in reversed(lines):
        if "Error" in l or "Exception" in l or "FAILED" in l:
            return l[-200:]
    return lines[-1][-200:] if lines else ""


def _probe_with_retry():
    """Retry the probe across a window; the tunnel is known-intermittent."""
    attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "5"))
    timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
    wait = int(os.environ.get("BENCH_PROBE_WAIT", "30"))
    diag = "no attempts"
    for i in range(attempts):
        ok, diag = _probe_once(timeout)
        if ok:
            return True, "ok (attempt %d/%d)" % (i + 1, attempts)
        sys.stderr.write("bench: probe %d/%d failed: %s\n" % (i + 1, attempts, diag))
        if diag.startswith("notpu:"):
            return False, "unreachable: " + diag[len("notpu: "):]
        if i + 1 < attempts:
            time.sleep(wait)
    return False, "unreachable: " + diag


def _run_worker(extra_env, timeout):
    """Run the measured workload in a subprocess; return parsed JSON or None."""
    env = dict(os.environ)
    env.update(extra_env)
    env["BENCH_WORKER"] = "1"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, timeout=timeout, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return None, "workload timed out after %ds" % timeout
    if r.returncode != 0:
        return None, "workload rc=%d: %s" % (
            r.returncode, _error_line(r.stderr or ""))
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line), None
        except ValueError:
            continue
    return None, "workload produced no JSON"


def _measure_k(trainer, batches, B, k, timed_steps, reps):
    """Throughput at k steps/dispatch: identical pre-fill + warmup schedule
    for every k (same batch sequence), then `reps` timed windows. Returns
    (per-k stats, per-table dedup stats); "examples_per_sec" is the best
    repetition (the tunnel TPU shows ±15% run-to-run noise on identical
    programs — the fastest window is the least-noisy estimate),
    mean/min/max expose the spread."""
    import jax

    from deeprec_tpu.training import stack_batches

    n = len(batches)
    # Identical budget state for every k: the trainer's EMA persists across
    # the K sweep, so without a reset later ks would pre-fill under the
    # previous k's engaged budget and could land in a different budget
    # bucket — conflating dispatch amortization with budget differences.
    trainer._unique_ema.clear()
    trainer._auto_frac.clear()
    trainer._make_jits()
    state = trainer.init(0)
    # Pre-fill: populate the table through the single-step path so every k
    # starts timing from the same table occupancy.
    for i in range(16):
        state, mets = trainer.train_step(state, batches[i % n])
    jax.block_until_ready(mets["loss"])
    if trainer.unique_budget is not None:
        # Fold the pre-fill's measured unique fractions into the budgets so
        # the warmed/timed windows run the hash dedup engine at-budget
        # (docs/perf.md); the one recompile lands in the warmup window.
        state, _ = trainer.update_budgets(state)

    steps_k = max(k, timed_steps - timed_steps % k)
    ndisp = steps_k // k
    if k == 1:
        def window(state):
            for i in range(steps_k):
                state, mets = trainer.train_step(state, batches[i % n])
            return state, mets
    else:
        stacked = [
            stack_batches([batches[(d * k + i) % n] for i in range(k)])
            for d in range(ndisp)
        ]

        def window(state):
            for d in range(ndisp):
                state, mets = trainer.train_steps(state, stacked[d])
            return state, mets

    # Warmup window: compiles the k-path, advances the same steps_k steps.
    state, mets = window(state)
    jax.block_until_ready(mets["loss"])

    # Steady-state compile budget: after the warmup window every timed rep
    # must be pure cache-hit dispatch — an XLA compile inside the timed
    # loop means something retraces per step (the DRT001 class) and the
    # throughput number is garbage. Smoke runs HARD-FAIL on it
    # (trace_guard raises); full runs record the count into the JSON,
    # where tools/roofline.py --assert-compiles gates it.
    from deeprec_tpu.analysis import trace_guard

    budget = 0 if os.environ.get("BENCH_SMOKE") == "1" else None
    times = []
    with trace_guard(max_compiles=budget, note=f"K={k} steady state") as g:
        for _ in range(reps):
            t0 = time.perf_counter()
            state, mets = window(state)
            jax.block_until_ready(mets["loss"])
            times.append(time.perf_counter() - t0)
    ex = [steps_k * B / t for t in times]
    return {
        "examples_per_sec": round(max(ex), 1),
        "mean": round(sum(ex) / len(ex), 1),
        "min": round(min(ex), 1),
        "max": round(max(ex), 1),
        "ms_per_step": round(min(times) / steps_k * 1e3, 3),
        "timed_steps": steps_k,
        "reps": reps,
        "steady_compiles": g.compiles,
    }, trainer.dedup_stats(state)


def _traffic_report(trainer, budget_mode, dedup_stats):
    """The traffic-diet artifact: modeled per-step embedding-engine bytes
    (before vs after the diet, at the measured single-device shape AND the
    reference sharded DLRM shape) plus MEASURED stablehlo gather/scatter
    counts of the single-table lookup+apply program, next to the model's
    expected counts. `tools/roofline.py --assert-traffic <json>` fails when
    model and measurement drift."""
    import jax
    import jax.numpy as jnp

    from deeprec_tpu.ops import dedup
    from deeprec_tpu.ops import traffic as T
    from deeprec_tpu.optim.apply import apply_gradients, ensure_slots

    # Measured unique fraction (auto budgets) scales the touched rows.
    fracs = [
        s["unique_fraction"] for s in dedup_stats.values()
        if s.get("unique_fraction")
    ]
    uf = round(sum(fracs) / len(fracs), 4) if fracs else 1.0

    slot_widths = tuple(
        w for (shape, _) in trainer.sparse_opt.slot_specs(16).values()
        for w in shape
    ) or (0,)
    shapes = {
        "measured_1dev": dict(num_shards=1, comm=None),
        "reference_8dev_allgather": dict(num_shards=8, comm="allgather"),
    }
    modeled = {}
    for name, kw in shapes.items():
        before = T.dlrm_reference_traffic(
            diet=False, exchange_dtype="float32", unique_fraction=uf,
            slot_widths=slot_widths, **kw,
        )
        after = T.dlrm_reference_traffic(
            diet=True, exchange_dtype="bfloat16", unique_fraction=uf,
            slot_widths=slot_widths, **kw,
        )
        modeled[name] = {
            "before_bytes": round(before["total_bytes"]),
            "after_bytes": round(after["total_bytes"]),
            "wire_after_bytes": round(after["wire_bytes"]),
            "reduction": round(
                1.0 - after["total_bytes"] / before["total_bytes"], 4
            ),
        }

    # Measured op counts: lower the single-table train lookup+apply at a
    # small static shape (op COUNTS are shape-independent) on both the
    # diet and the legacy-apply arm.
    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.embedding.table import EmbeddingTable

    t = EmbeddingTable(TableConfig(name="_traffic_probe", dim=16,
                                   capacity=1 << 12))
    s = ensure_slots(t, t.create(), trainer.sparse_opt)
    ids = jnp.arange(256, dtype=jnp.int32)
    budgeted = budget_mode != "off"
    U = dedup.resolve_size(128, 256) if budgeted else None

    def prog(s, ids, diet):
        s, res = t._lookup_unique_impl(s, ids, jnp.int32(0), True, -1, U)
        g = jnp.ones_like(res.embeddings, jnp.float32)
        return apply_gradients(t, s, trainer.sparse_opt, res, g, step=0,
                               reuse_rows=diet, stamp_meta=not diet)

    n_slots = sum(1 for n in s.slots if not n.startswith("scalar/"))
    ops = {}
    for arm, diet in (("diet", True), ("legacy_apply", False)):
        txt = jax.jit(  # noqa: DRT001 — built once per bench invocation, reused across the timed loop
            lambda s, ids, d=diet: prog(s, ids, d)
        ).lower(s, ids).as_text()
        ops[arm] = T.count_stablehlo_ops(txt)
    return {
        "unique_fraction": uf,
        "engine_bytes_per_step": modeled["measured_1dev"]["after_bytes"],
        "modeled": modeled,
        "ops_measured": ops,
        "ops_model": {
            "diet": T.expected_lookup_apply_ops(
                diet=True, budgeted=budgeted, n_row_slots=n_slots),
            "legacy_apply": T.expected_lookup_apply_ops(
                diet=False, budgeted=budgeted, n_row_slots=n_slots),
        },
        "budgeted": budgeted,
    }


def _skew_bench_model(dims):
    """Linear model over T skewed single-hot tables + 2 dense features —
    shared by the placement grid arm and the drift arm (same structure,
    different dims/zipf constants)."""
    import jax
    import jax.numpy as jnp

    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.features import DenseFeature, SparseFeature

    t_tables = len(dims)

    class SkewModel:
        features = [
            SparseFeature(
                f"C{i+1}",
                table=TableConfig(
                    name=f"C{i+1}", dim=dims[i], capacity=1 << 13
                ),
            )
            for i in range(t_tables)
        ] + [DenseFeature("I1", 1), DenseFeature("I2", 1)]

        def init(self, key):
            return {
                "w": jax.random.normal(key, (sum(dims) + 2,)) * 0.05
            }

        def apply(self, dense, inputs, train):
            x = jnp.concatenate(
                [inputs.pooled[f"C{i+1}"] for i in range(t_tables)]
                + [inputs.dense["I1"], inputs.dense["I2"]],
                -1,
            )
            return x @ dense["w"]

    return SkewModel()


def _placement_workload():
    """Skew-aware placement bench (round 12): measured per-shard
    exchange-bytes imbalance, uniform hash vs the adopted ShardPlan, on a
    skewed multi-table 8-shard workload.

    Runs in its OWN subprocess (stdout = one JSON line) because it needs
    the virtual 8-device CPU mesh — forcing 8 host devices in the main
    bench process would change the headline single-device measurement.

    Workload: 4 single-hot tables with heterogeneous dims (64/48/16/8 —
    per-table row bytes are a placer input, ops/traffic.py
    exchange_row_bytes) drawing per-table bounded-zipf ids from ONE shared
    raw id space (`SyntheticCriteo(offset_ids=False)`): every table's head
    is the same raw ids, so under `hash_shard` they hammer the same owner
    shards — the correlated-head case the plan's owner-offset rotation +
    hot-key re-routing flattens. Protocol: prefill window under uniform
    routing (fills the freq/owner counters), measure imbalance_before +
    uniform step time; `update_placement` adopts the plan (mode="uniform"
    skips adoption — the comparison arm); measure imbalance_after + plan
    step time on the SAME batch sequence. `tools/roofline.py
    --assert-imbalance` gates the ratio and the step-time bound in CI."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.features import DenseFeature, SparseFeature
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch

    mode = os.environ.get("BENCH_PLACEMENT", "grid")
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    N = 8
    ZIPF = [2.6, 2.4, 2.2, 2.0]
    DIMS = [64, 48, 16, 8]
    T_TABLES = len(ZIPF)
    B = 128
    n_batches = 8 if smoke else 12
    reps = 2 if smoke else 3

    mesh = make_mesh(N)
    gen = SyntheticCriteo(
        batch_size=B, num_cat=T_TABLES, num_dense=2, vocab=200_000,
        seed=7, zipf_a=ZIPF, offset_ids=False,
    )
    sb = [
        shard_batch(mesh, {k: jnp.asarray(v) for k, v in gen.batch().items()})
        for _ in range(n_batches)
    ]
    tr = ShardedTrainer(
        _skew_bench_model(DIMS), Adagrad(lr=0.1), mesh=mesh,
        placement="plan",
    )
    st = tr.init(0)

    def per_shard_bytes(state):
        per = np.zeros(N)
        for _, d in tr.dedup_stats(state).items():
            ps = d.get("per_shard")
            if ps:
                per += np.asarray(ps["exchange_bytes"])
        return per

    def window(state):
        """One timed pass over the batch sequence (counters accumulate)."""
        t0 = time.perf_counter()
        for i in range(n_batches):
            state, mets = tr.train_step(state, sb[i])
        jax.block_until_ready(mets["loss"])
        return state, (time.perf_counter() - t0) / n_batches * 1e3

    def measure(state):
        """Reset the owner counters, run `reps` timed windows; imbalance
        comes off the counters the windows accumulated."""
        state, _ = tr.update_budgets(state)
        times = []
        for _ in range(reps):
            state, ms = window(state)
            times.append(ms)
        per = per_shard_bytes(state)
        from deeprec_tpu.ops import traffic as T

        return state, T.shard_imbalance(per), per, round(min(times), 3)

    # Prefill: populate tables + freq counters (and compile) under the
    # uniform default plan, then measure the uniform arm.
    st, _ = window(st)
    st, imb_before, per_before, ms_uniform = measure(st)

    report = {
        "mode": mode,
        "device": jax.devices()[0].platform,
        "num_shards": N,
        "num_tables": T_TABLES,
        "zipf": ZIPF,
        "dims": DIMS,
        "batch": B,
        "imbalance_before": round(imb_before, 4),
        "step_ms": {"uniform": ms_uniform},
        "per_shard_exchange_bytes": {
            "uniform": [round(float(x)) for x in per_before]
        },
    }
    if mode != "uniform":
        st, plan_rep = tr.update_placement(st)
        adopted = [b for b, r in plan_rep.items() if r.get("adopted")]
        st, imb_after, per_after, ms_plan = measure(st)
        report.update({
            "imbalance_after": round(imb_after, 4),
            "imbalance_ratio": round(imb_before / max(imb_after, 1e-9), 3),
            "adopted_bundles": adopted,
            "moved_rows": sum(
                r.get("moved", 0) for r in plan_rep.values()
            ),
            "hot_keys": (tr.last_placement or {}).get("hot_keys"),
            "modeled": {
                "imbalance_before":
                    (tr.last_placement or {}).get("imbalance_current"),
                "imbalance_after":
                    (tr.last_placement or {}).get("imbalance_candidate"),
            },
        })
        report["step_ms"]["plan"] = ms_plan
        report["per_shard_exchange_bytes"]["plan"] = [
            round(float(x)) for x in per_after
        ]
    if mode in ("grid", "drift"):
        report["drift"] = _placement_drift_arm(smoke)
    print(json.dumps(report))


def _placement_drift_arm(smoke):
    """Drifting-skew placement arm (round 19): the hot-key set rotates
    mid-stream (`SyntheticCriteo(zipf_rotate_every=)`) under a live
    `placement="plan"` trainer on the budgeted a2a exchange — the
    workload the drift-driven replanner exists for.

    Protocol: one dominant-dim zipf-head table + three light tables in a
    shared raw id space; windows of train steps with `maintain()` after
    each (the maybe_replan drift gate runs exactly as production would).
    The trainer first adopts a plan off the early windows; at the
    midpoint the generator rotates the hot set, the adopted plan goes
    stale, the measured imbalance spikes, and the replanner must catch
    it AUTOMATICALLY — hysteresis-triggered, amortization-approved,
    never forced. Records the per-window imbalance trajectory, the
    replan/migration accounting, the a2a overflow counters (must be 0:
    the drift-safety margin of the per-dest budget covers the stale
    window), and the per-dest-budget wire diet next to the v1
    global-headroom model (measured bucket == modeled vector max).
    `tools/roofline.py --assert-imbalance` gates all of it in CI."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.features import DenseFeature, SparseFeature
    from deeprec_tpu.ops import traffic as T
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
    from deeprec_tpu.parallel.placement import ReplanConfig

    N = 8
    ZIPF = [3.0, 1.6, 1.4, 1.2]
    DIMS = [128, 8, 8, 8]
    T_TABLES = len(ZIPF)
    B = 512
    spw = 2 if smoke else 3  # steps per maintain window
    # ONE rotation at the midpoint: pre == post keeps exactly one
    # rotate_every boundary inside the run (the generator rotates at
    # every multiple).
    pre = 4 if smoke else 5  # windows before the hot set rotates
    post = 4 if smoke else 5  # windows after

    mesh = make_mesh(N)
    gen = SyntheticCriteo(
        batch_size=B, num_cat=T_TABLES, num_dense=2, vocab=200_000,
        seed=11, zipf_a=ZIPF, offset_ids=False,
        zipf_rotate_every=pre * spw,
    )
    tr = ShardedTrainer(
        _skew_bench_model(DIMS), Adagrad(lr=0.1), mesh=mesh, comm="a2a",
        placement="plan", placement_hot_budget=64,
        replan=ReplanConfig(threshold=1.4, sustain=1, cooldown=1,
                            horizon_steps=20_000),
    )
    st = tr.init(0)

    def window_imbalance(state):
        per = np.zeros(N)
        for _, d in tr.dedup_stats(state).items():
            ps = d.get("per_shard") if isinstance(d, dict) else None
            if ps:
                per += np.asarray(ps["exchange_bytes"])
        return T.shard_imbalance(per)

    trajectory = []
    post_drift_auto = 0
    last_sb = None
    for w in range(pre + post):
        for _ in range(spw):
            last_sb = shard_batch(
                mesh, {k: jnp.asarray(v) for k, v in gen.batch().items()}
            )
            st, mets = tr.train_step(st, last_sb)
        jax.block_until_ready(mets["loss"])
        imb = window_imbalance(st)
        before = int(tr._replan_stats["replans"])
        st, _ = tr.maintain(st)
        replanned = int(tr._replan_stats["replans"]) > before
        if replanned and w >= pre:
            post_drift_auto += 1
        trajectory.append({
            "window": w, "imbalance": round(imb, 4),
            "post_drift": w >= pre, "replanned": replanned,
        })

    # One settling step AFTER the last maintain(): an adoption on the
    # final window updates plan_dest_hot/plan_hot_count and rebuilds the
    # jits, but last_a2a_budgets/bucket/unique only refresh at the next
    # TRACE — without this step the measured==modeled budget assert
    # below would compare the NEW plan's model against the OLD plan's
    # compiled bucket and fail spuriously. Re-runs the LAST drawn batch
    # (never a fresh draw — the next index would cross a SECOND
    # rotate_every boundary and train one step on a third hot set the
    # protocol never replans).
    st, mets = tr.train_step(st, last_sb)
    jax.block_until_ready(mets["loss"])

    # Post-drift peak = worst window up to and including the first
    # post-drift replan; recovery = the final window (plan re-settled).
    post_w = [t for t in trajectory if t["post_drift"]]
    peak = 0.0
    for t in post_w:
        peak = max(peak, t["imbalance"])
        if t["replanned"]:
            break
    recovered = post_w[-1]["imbalance"] if post_w else None

    overflow = sum(
        int(np.sum(np.asarray(jax.device_get(ts.a2a_overflow))))
        for ts in st.tables.values()
    )
    # Per-dest budget diet: the bucket each bundle's trace compiled
    # (measured) vs the model's vector max (must agree exactly) vs the
    # v1 global-headroom bucket, in wire bytes (id/count + both payload
    # directions, ops/traffic.py a2a_exchange_wire_bytes).
    budgets = {}
    wire_plan = wire_global = 0.0
    budgets_match = True
    for bname, b in tr.bundles.items():
        sh = tr.sharded[bname]
        bp = tr._plans.get(bname)
        U = sh.last_a2a_unique
        dest_hot = sh.plan_dest_hot
        hot_max = int(bp.dest_hot_counts().max()) if bp else 0
        modeled = T.a2a_dest_budgets(
            unique=U, num_shards=N, slack=sh.a2a_slack,
            dest_hot=dest_hot, hot_count=sh.plan_hot_count,
        )
        match = (
            int(modeled.max()) == sh.last_a2a_bucket
            and np.array_equal(modeled, np.asarray(sh.last_a2a_budgets))
        )
        budgets_match &= match
        g_bucket = T.a2a_bucket_rows_global(
            unique=U, num_shards=N, slack=sh.a2a_slack, hot_max=hot_max,
        )
        n_members = len(b.features) if b.stacked else 1
        cfg = b.table.cfg
        wire_b = 2 if cfg.exchange_dtype == "bfloat16" else 4
        wire_plan += n_members * T.a2a_exchange_wire_bytes(
            bucket_rows=sh.last_a2a_bucket, num_shards=N, dim=cfg.dim,
            wire_bytes=wire_b,
        )
        wire_global += n_members * T.a2a_exchange_wire_bytes(
            bucket_rows=g_bucket, num_shards=N, dim=cfg.dim,
            wire_bytes=wire_b,
        )
        budgets[bname] = {
            "unique": U,
            "bucket_rows": sh.last_a2a_bucket,
            "modeled_bucket_rows": int(modeled.max()),
            "dest_budgets": [int(x) for x in modeled],
            "global_headroom_rows": g_bucket,
            "hot_max": hot_max,
            "measured_eq_modeled": match,
        }
    return {
        "batch": B, "num_shards": N, "zipf": ZIPF, "dims": DIMS,
        "steps_per_window": spw, "windows_pre": pre, "windows_post": post,
        "rotate_at_step": pre * spw,
        "trajectory": trajectory,
        "peak_post_drift": round(peak, 4),
        "recovered_imbalance": (
            round(recovered, 4) if recovered is not None else None
        ),
        "replans": {
            "total": int(tr._replan_stats["replans"]),
            "forced": int(tr._replan_stats["forced_replans"]),
            "post_drift_auto": post_drift_auto,
        },
        "migration_rows": int(tr._replan_stats["migration_rows"]),
        "migration_bytes": float(tr._replan_stats["migration_bytes"]),
        "a2a_overflow": overflow,
        "budgets": budgets,
        "budgets_measured_eq_modeled": bool(budgets_match),
        "wire_bytes_per_dest_model": round(wire_plan, 1),
        "wire_bytes_global_headroom_model": round(wire_global, 1),
        "cost_model": tr.cost_model.info(),
    }


def _mesh_workload():
    """Pod-scale 2-D mesh bench (round 19): flat 1-D exchange vs the
    hierarchical two-tier exchange on the same high-overlap stream.

    Runs in its OWN subprocess (stdout = one JSON line) on the forced
    virtual 8-device CPU mesh, like the placement arm. Workload: the
    skew-bench model drawing per-table zipf ids from one SMALL shared id
    space (`vocab=1500, offset_ids=False`) so devices inside a host group
    see heavily overlapping id sets — the regime the intra-tier
    aggregation exists for (a disjoint stream would make U_g = intra·U
    and the hierarchy pointless).

    Arms (mode "grid" runs all; "1d"/"2d" subsets):
      1d_a2a      make_mesh(8),        comm="a2a"     — the flat baseline
      2d_hier     make_mesh_2d(4, 2),  comm="hier"    — two-tier exchange
      2d_nested   same mesh/comm, pipeline_mode="nested" K-scan — the
                  inter-tier id exchange of batch t+1 hoisted behind
                  dense(t) across BOTH tiers
    Every arm records its first-step loss from a fresh init (the forward
    is exact under the hierarchy — one contributor per psum_scatter
    position — so all arms must agree BITWISE), single-step and K-scan
    ms/step under trace_guard (steady compiles: contract 0), and the a2a
    overflow counters (contract 0).

    The hier arm also records the per-tier wire model at the measured
    unique budget — `ops/traffic.py hier_exchange_bytes` next to
    `flat_exchange_tier_bytes` (the flat a2a mapped onto the same 2×4
    topology) — plus the compiled inter bucket vs the model's vector max
    (must agree exactly, same discipline as the drift arm's budgets).
    `tools/roofline.py --assert-hierarchy` gates: inter-tier modeled
    bytes ≤ total_flat/intra AND ≤ 0.5× flat inter-host bytes, 0
    overflow, 0 steady compiles, bitwise loss parity, nested K-scan
    within tolerance of the unpipelined hier K-scan."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeprec_tpu.analysis import trace_guard
    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.ops import traffic as T
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.parallel import (
        ShardedTrainer, make_mesh, make_mesh_2d, shard_batch,
    )
    from deeprec_tpu.training import stack_batches

    mode = os.environ.get("BENCH_MESH", "grid")
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    N, INTRA, INTER = 8, 4, 2
    GROUP_FACTOR = 1.5
    SLACK = 2.0
    ZIPF = [2.2, 2.0, 1.8, 1.6]
    DIMS = [32, 16, 16, 8]
    # Batch large enough that the per-device unique budget clears the
    # multiple-of-8 bucket rounding by a wide margin — at tiny U the
    # rounding, not the hierarchy, sets the inter bucket and the modeled
    # ratios are granularity noise.
    B = 1024
    K = 4
    n_batches = 8
    prefill = 4 if smoke else 8
    reps = 2 if smoke else 3
    timed_steps = 4 if smoke else 8

    gen = SyntheticCriteo(
        batch_size=B, num_cat=len(DIMS), num_dense=2, vocab=1500,
        seed=5, zipf_a=ZIPF, offset_ids=False,
    )
    host_batches = [
        {k: jnp.asarray(v) for k, v in gen.batch().items()}
        for _ in range(n_batches)
    ]

    def run_arm(mesh, comm, pipeline_mode="off", group_factor=None):
        tr = ShardedTrainer(
            _skew_bench_model(DIMS), Adagrad(lr=0.1), mesh=mesh, comm=comm,
            a2a_slack=SLACK, pipeline_mode=pipeline_mode, pipeline_chunks=2,
            hier_group_factor=group_factor,
        )
        sb = [shard_batch(mesh, b) for b in host_batches]
        st = tr.init(0)
        # First step from a FRESH init on the shared batch: the parity
        # anchor (forward is exact, so every arm must agree bitwise).
        st, mets = tr.train_step(st, sb[0])
        first_loss = float(mets["loss"])
        for i in range(1, prefill):
            st, mets = tr.train_step(st, sb[i % n_batches])
        jax.block_until_ready(mets["loss"])

        # Timed single-step windows. Record-only guard (the gate reads
        # the count): the arm is measured, not hard-failed mid-bench.
        times = []
        with trace_guard(max_compiles=None, note=f"mesh {comm} step") as g1:
            for _ in range(reps):
                t0 = time.perf_counter()
                for i in range(timed_steps):
                    st, mets = tr.train_step(st, sb[i % n_batches])
                jax.block_until_ready(mets["loss"])
                times.append((time.perf_counter() - t0) / timed_steps * 1e3)
        # Snapshot NOW: .compiles reads the process-wide counter live, so
        # a late read would absorb the scan warmup's legitimate compiles.
        step_compiles = g1.compiles
        # K-step scan arm (where pipeline_mode engages).
        sh = NamedSharding(mesh, P(None, tr.axis))
        stacked = [
            jax.device_put(
                stack_batches(
                    [host_batches[(d * K + i) % n_batches] for i in range(K)]
                ),
                sh,
            )
            for d in range(2)
        ]
        st, mets = tr.train_steps(st, stacked[0])  # warm: compile K-path
        jax.block_until_ready(mets["loss"])
        scan_times = []
        with trace_guard(max_compiles=None, note=f"mesh {comm} scan") as g2:
            for _ in range(reps):
                t0 = time.perf_counter()
                for d in range(len(stacked)):
                    st, mets = tr.train_steps(st, stacked[d])
                jax.block_until_ready(mets["loss"])
                scan_times.append(
                    (time.perf_counter() - t0) / (len(stacked) * K) * 1e3
                )
        scan_compiles = g2.compiles
        overflow = sum(
            int(np.sum(np.asarray(jax.device_get(ts.a2a_overflow))))
            for ts in st.tables.values()
        )
        return {
            "first_loss": first_loss,
            "step_ms": round(min(times), 3),
            "scan_ms_per_step": round(min(scan_times), 3),
            "steady_compiles": step_compiles + scan_compiles,
            "overflow": overflow,
        }, tr

    arms = {}
    hier_tr = None
    if mode in ("1d", "grid"):
        arms["1d_a2a"], _ = run_arm(make_mesh(N), "a2a")
    if mode in ("2d", "grid"):
        mesh2 = make_mesh_2d(INTRA, INTER)
        arms["2d_hier"], hier_tr = run_arm(
            mesh2, "hier", group_factor=GROUP_FACTOR
        )
        arms["2d_nested"], _ = run_arm(
            make_mesh_2d(INTRA, INTER), "hier", pipeline_mode="nested",
            group_factor=GROUP_FACTOR,
        )

    report = {
        "mode": mode,
        "device": jax.devices()[0].platform,
        "num_devices": N,
        "shape_2d": {"intra": INTRA, "inter": INTER},
        "group_factor": GROUP_FACTOR,
        "slack": SLACK,
        "zipf": ZIPF, "dims": DIMS, "batch": B,
        "steps_per_dispatch": K,
        "arms": arms,
        "first_loss_equal": len({a["first_loss"] for a in arms.values()}) <= 1,
        "overflow": sum(a["overflow"] for a in arms.values()),
        "trace_guard": {
            "budget": 0,
            "steady_state_compiles": sum(
                a["steady_compiles"] for a in arms.values()
            ),
        },
    }
    if hier_tr is not None:
        # Per-tier wire model at each bundle's MEASURED unique budget,
        # next to the flat a2a mapped onto the same topology; the
        # compiled inter bucket must equal the model's vector max.
        tiers = {}
        hier_intra = hier_inter = 0.0
        flat_intra = flat_inter = flat_total = 0.0
        buckets_match = True
        for bname, b in hier_tr.bundles.items():
            sh_t = hier_tr.sharded[bname]
            U = sh_t.last_a2a_unique
            cfg = b.table.cfg
            wire_b = 2 if cfg.exchange_dtype == "bfloat16" else 4
            n_members = len(b.features) if b.stacked else 1
            hb = T.hier_exchange_bytes(
                unique=U, intra=INTRA, inter=INTER, dim=cfg.dim,
                wire_bytes=wire_b, slack=sh_t.a2a_slack,
                group_factor=sh_t.hier_group_factor,
                dest_hot=sh_t.plan_dest_hot, hot_count=sh_t.plan_hot_count,
            )
            fb = T.flat_exchange_tier_bytes(
                unique=U, num_shards=N, intra=INTRA, comm="a2a",
                dim=cfg.dim, wire_bytes=wire_b, slack=sh_t.a2a_slack,
            )
            match = int(hb["bucket_rows"]) == sh_t.last_a2a_bucket
            buckets_match &= match
            hier_intra += n_members * hb["intra_bytes"]
            hier_inter += n_members * hb["inter_bytes"]
            flat_intra += n_members * fb["intra_bytes"]
            flat_inter += n_members * fb["inter_bytes"]
            flat_total += n_members * fb["total_bytes"]
            tiers[bname] = {
                "unique": U,
                "group_unique_budget": int(hb["group_unique_budget"]),
                "bucket_rows": sh_t.last_a2a_bucket,
                "modeled_bucket_rows": int(hb["bucket_rows"]),
                "measured_eq_modeled": match,
            }
        report["hier"] = {
            "per_bundle": tiers,
            "modeled_bytes": {
                "hier_intra": round(hier_intra),
                "hier_inter": round(hier_inter),
                "flat_a2a_intra": round(flat_intra),
                "flat_a2a_inter": round(flat_inter),
                "flat_a2a_total": round(flat_total),
            },
            "inter_ratio_vs_flat_inter": round(
                hier_inter / max(flat_inter, 1e-9), 4
            ),
            "inter_ratio_vs_flat_total_over_intra": round(
                hier_inter / max(flat_total / INTRA, 1e-9), 4
            ),
            "buckets_measured_eq_modeled": bool(buckets_match),
        }
    print(json.dumps(report))


def _run_mesh_worker():
    """Spawn _mesh_workload on a forced 8-device CPU mesh; returns its
    JSON section (or an error record — the bench JSON stays usable)."""
    env = dict(os.environ)
    env.pop("BENCH_WORKER", None)
    env["BENCH_MESH_WORKER"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        t for t in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in t
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"]
    )
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, timeout=1200, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return {"error": "mesh workload timed out"}
    if r.returncode != 0:
        return {"error": "mesh workload rc=%d: %s" % (
            r.returncode, _error_line(r.stderr or ""))}
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return {"error": "mesh workload produced no JSON"}


def _run_placement_worker():
    """Spawn _placement_workload on a forced 8-device CPU mesh; returns
    its JSON section (or an error record — the bench JSON stays usable)."""
    env = dict(os.environ)
    env.pop("BENCH_WORKER", None)
    env["BENCH_PLACEMENT_WORKER"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    # Force EXACTLY 8 virtual devices: an inherited count (a 1- or
    # 4-device flag from some other arm's environment) would fail
    # make_mesh(8) in the worker, so any existing token is replaced, not
    # respected.
    flags = [
        t for t in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in t
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"]
    )
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, timeout=1200, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return {"error": "placement workload timed out"}
    if r.returncode != 0:
        return {"error": "placement workload rc=%d: %s" % (
            r.returncode, _error_line(r.stderr or ""))}
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return {"error": "placement workload produced no JSON"}


def _ckpt_report():
    """Host-choreography stall accounting (round 9): what a checkpoint /
    multi-tier sync costs the TRAINING THREAD, sync vs async, plus the
    incremental-save transfer diet (device-compacted dirty rows vs the
    legacy full-table device->host pull). Small dedicated model — the
    numbers are stall ratios, not throughput, so smoke-scale is fine."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = WDL(emb_dim=16, capacity=1 << 14, hidden=(32,), num_cat=4,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1))
    gen = SyntheticCriteo(batch_size=512, num_cat=4, num_dense=2,
                          vocab=6000, seed=0)
    batches = [
        {k: jnp.asarray(v) for k, v in gen.batch().items()} for _ in range(4)
    ]
    st = tr.init(0)
    for b in batches:
        st, mets = tr.train_step(st, b)
    jax.block_until_ready(mets["loss"])

    tmp = tempfile.mkdtemp(prefix="deeprec_bench_ckpt_")
    try:
        ck = CheckpointManager(os.path.join(tmp, "sync"), tr)
        cka = CheckpointManager(os.path.join(tmp, "async"), tr)
        report = {"ckpt_stall_ms": {}, "incr_transfer_bytes": {}}

        st, _ = ck.save(st)
        report["ckpt_stall_ms"]["sync_full"] = ck.last_save["stall_ms"]
        full_bytes = ck.last_save["transfer_bytes"]
        _, _ = cka.save_async(st)
        report["ckpt_stall_ms"]["async_full"] = cka.last_save["stall_ms"]
        cka.wait()

        # dirty a fraction of the table, then delta-save both ways
        st, mets = tr.train_step(st, batches[0])
        jax.block_until_ready(mets["loss"])
        st2, _ = ck.save_incremental(st)
        report["ckpt_stall_ms"]["sync_incr"] = ck.last_save["stall_ms"]
        incr_bytes = ck.last_save["transfer_bytes"]
        _, _ = cka.save_incremental_async(st)
        report["ckpt_stall_ms"]["async_incr"] = cka.last_save["stall_ms"]
        cka.wait()
        report["incr_transfer_bytes"] = {
            "full_tables": int(full_bytes),
            "dirty_compacted": int(incr_bytes),
            "reduction": round(1.0 - incr_bytes / max(full_bytes, 1), 4),
        }

        # multi-tier migration: sync stall vs overlapped extraction
        from deeprec_tpu.config import (
            EmbeddingVariableOption, StorageOption, TableConfig,
        )
        from deeprec_tpu.embedding.multi_tier import MultiTierTable
        from deeprec_tpu.embedding.table import EmbeddingTable

        def tier_run(use_async):
            cfg = TableConfig(
                name="bench_tier", dim=16, capacity=1 << 12,
                ev=EmbeddingVariableOption(storage=StorageOption(
                    storage_type="hbm_dram")),
            )
            t = EmbeddingTable(cfg)
            mt = MultiTierTable(t, high_watermark=0.7, low_watermark=0.5)
            s = t.create()
            s, res = t.lookup_unique(
                s, jnp.arange(3500, dtype=jnp.int32), step=0
            )
            jax.block_until_ready(res.embeddings)
            t0 = time.perf_counter()
            if use_async:
                s, _ = mt.sync_async(s, step=1)
            else:
                s, _ = mt.sync(s, step=1)
            stall = (time.perf_counter() - t0) * 1e3
            if use_async:
                mt.drain(s)
            return round(stall, 3)

        report["sync_stall_ms"] = {
            "sync": tier_run(False), "async": tier_run(True),
        }
        return report
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _tier_paging_report():
    """Overlapped tier paging arm (round 20): rotated-zipf stream sized to
    force demotion, hot set rotated each maintain window so demoted keys
    reappear MID-window. Two arms on the identical stream — paging off
    (promotes only at maintain cadence) vs paging on (TierPrefetcher
    gathers + dispatch-boundary folds) — recording the fresh-init
    (optimizer-state-loss) rate, fold bytes, training-thread stall, step
    time, and steady-state fold compiles. Gated by tools/roofline.py
    --assert-tier: loss rate >=10x lower, 0 steady compiles, fold stall
    <= the async-round stall, step time parity."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeprec_tpu.analysis import trace_guard
    from deeprec_tpu.config import EmbeddingVariableOption, StorageOption
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    smoke = os.environ.get("BENCH_SMOKE") == "1"
    B = 256 if smoke else 512
    warm_steps = 10
    timed_steps = 24 if smoke else 48
    maintain_every = 8
    capacity = 1 << 11
    vocab = 8000
    window = 3000  # uniques live in a rotating zipf window of this width
    rotate = 500   # window shift per maintain window

    steps = warm_steps + timed_steps
    rng = np.random.default_rng(7)

    def zipf_ids(n):
        # a=1.1: flat-tailed — each window's distinct set overruns the
        # demote watermark, and the cold tail keeps re-appearing so the
        # off arm pays fresh re-inits mid-window
        z = rng.zipf(1.1, size=n)
        return (z - 1) % window

    batches = []
    for t in range(steps):
        base = (t // maintain_every) * rotate
        cats = [
            ((zipf_ids(B) + base) % vocab).astype(np.int32)
            for _ in range(2)
        ]
        batches.append({
            "label": rng.integers(0, 2, B).astype(np.float32),
            "I1": rng.normal(size=(B, 1)).astype(np.float32),
            "I2": rng.normal(size=(B, 1)).astype(np.float32),
            "C1": cats[0], "C2": cats[1],
        })

    def build():
        ev = EmbeddingVariableOption(
            storage=StorageOption(storage_type="hbm_dram")
        )
        model = WDL(emb_dim=16, capacity=capacity, hidden=(32,),
                    num_cat=2, num_dense=2, ev=ev)
        tr = Trainer(model, Adagrad(lr=0.1))
        return tr, tr.init(0)

    def resident_keys(tr, cache):
        """Tier-resident key set, recomputed only when a boundary/fold
        changed the stores (revision-keyed — the same discipline the row
        cache uses)."""
        rev = sum(mt._tier_rev for mt in getattr(tr, "_tiers", {}).values())
        if cache.get("rev") != rev:
            keys = set()
            for mt in getattr(tr, "_tiers", {}).values():
                if mt.host is not None:
                    keys.update(int(k) for k in mt.host.export()[0])
                if mt.disk is not None:
                    keys.update(int(k) for k in mt.disk.index)
            cache["rev"], cache["keys"] = rev, keys
        return cache["keys"]

    def run_arm(paging):
        tr, st = build()
        pager = tr.enable_tier_paging(depth=16, chunk=256) if paging else None
        res_cache = {}
        loss_touches = positions = 0
        step_ms = []
        steady_compiles = 0
        warmed = False
        try:
            src = tr.stage(iter(batches), depth=2)
            for i, b in enumerate(src):
                timed = i >= warm_steps
                if paging and timed and not warmed:
                    # pre-compile the fold programs: the first demote (and
                    # so the first real fold) may land inside the timed
                    # window, and a cold compile is not a steady-state one
                    tr.warm_tier_folds(st)
                    warmed = True
                if pager is not None:
                    pager.drain(10.0)
                if paging:
                    # guard ONLY the fold path: the fixed-chunk compile
                    # contract is the fold program's, not maintain's
                    # (demote shapes recompile at their own cadence)
                    if timed:
                        with trace_guard(
                            max_compiles=None, note="tier paging fold"
                        ) as g:
                            st, _ = tr.fold_tier_prefetch(st)
                        steady_compiles += g.compiles
                    else:
                        st, _ = tr.fold_tier_prefetch(st)
                # step timing excludes the fold — fold cost is reported
                # separately as fold_stall_ms
                t0 = time.perf_counter()
                st, mets = tr.train_step(st, b)
                jax.block_until_ready(mets["loss"])
                if timed:
                    step_ms.append((time.perf_counter() - t0) * 1e3)
                # fresh-init accounting AFTER the folds this step saw:
                # a batch position hitting a tier-resident key trains from
                # a re-initialized row — lost optimizer state
                if timed:
                    ids = np.concatenate([
                        np.asarray(jax.device_get(b["C1"])),
                        np.asarray(jax.device_get(b["C2"])),
                    ]).astype(np.int64)
                    res = resident_keys(tr, res_cache)
                    if res:
                        loss_touches += int(np.isin(
                            ids, np.fromiter(res, np.int64, len(res))
                        ).sum())
                    positions += ids.size
                if (i + 1) % maintain_every == 0:
                    st, _ = tr.maintain(st, tier_async=True)
            for mt in getattr(tr, "_tiers", {}).values():
                mt._settle()  # join any in-flight round before reading stalls
            rec = {
                "fresh_init_rate": round(loss_touches / max(positions, 1), 6),
                "loss_touches": loss_touches,
                "positions": positions,
                "step_ms": round(float(np.mean(step_ms)), 3),
                "sync_stall_ms": round(tr.tier_stall_ms(), 3),
            }
            if paging:
                stats = tr.tier_paging_stats()
                rec.update(
                    fold_stall_ms=round(stats["fold_stall_ms"], 3),
                    folded_rows=int(stats["folded_rows"]),
                    fold_bytes=int(stats["fold_bytes"]),
                    gather_errors=int(stats["gather_errors"]),
                    dropped_batches=int(stats["dropped_batches"]),
                    steady_compiles=steady_compiles,
                )
            return rec
        finally:
            if pager is not None:
                tr.close_tier_paging()

    off = run_arm(paging=False)
    on = run_arm(paging=True)
    r0, r1 = off["fresh_init_rate"], on["fresh_init_rate"]
    return {
        "stream": {
            "batch": B, "timed_steps": timed_steps, "vocab": vocab,
            "zipf_window": window, "rotate_per_window": rotate,
            "maintain_every": maintain_every, "capacity": capacity,
        },
        "off": off,
        "on": on,
        # the headline: optimizer-state-loss suppression from paging
        "loss_factor": round(r0 / r1, 2) if r1 > 0 else None,
        "step_time_ratio": round(on["step_ms"] / max(off["step_ms"], 1e-9), 4),
    }


def _profile_phases(trainer, batches):
    """Host-timed per-phase breakdown (training/profiler.py): jitted
    sub-programs isolate the sparse phases, deltas attribute the rest."""
    import jax
    import jax.numpy as jnp

    from deeprec_tpu.training.profiler import PhaseProfiler

    state = trainer.init(0)
    for i in range(4):
        state, mets = trainer.train_step(state, batches[i % len(batches)])
    jax.block_until_ready(mets["loss"])

    # The phase sub-programs DONATE the table pytree (like the step path
    # does) — without donation the output materializes a full copy of
    # every table per call and the copy, not the phase, dominates.
    lookup_jit = jax.jit(  # noqa: DRT001 — built once per bench invocation, reused across the timed loop
        lambda tables, b, step: trainer._lookup_all(tables, b, step, True)[0],
        donate_argnums=0,
    )
    # The hoistable routing phase (id dedup + id exchange; ids only, no
    # table state) — what pipeline_mode="lookahead" overlaps with the
    # dense compute. Timed standalone so the overlap model has a number.
    route_jit = jax.jit(lambda b: trainer._route_all(b, True))  # noqa: DRT001 — built once per bench invocation, reused across the timed loop

    def sparse(tables, b, step):
        tables, views, bundle_res = trainer._lookup_all(
            tables, b, step, True
        )
        g = {n: jnp.ones_like(v[0], jnp.float32) for n, v in views.items()}
        return trainer._apply_all(tables, bundle_res, g, step,
                                  jnp.float32(trainer.sparse_opt.lr))

    sparse_jit = jax.jit(sparse, donate_argnums=0)  # noqa: DRT001 — built once per bench invocation, reused across the timed loop
    prof = PhaseProfiler()
    b0 = batches[0]
    # Full-step phase FIRST: the sub-programs below then take over (and
    # donate) the final state's table buffers.
    for i in range(8):
        b = batches[i % len(batches)]
        with prof.phase("step", block=None):
            state, mets = trainer.train_step(state, b)
            jax.block_until_ready(mets["loss"])
    # Fresh host-round-tripped scalar: train_step donated the state (and
    # its step buffer) every iteration above.
    step0 = jnp.asarray(int(state.step), jnp.int32)
    # compile outside the timed loop; thread the donated tables through
    tables = lookup_jit(dict(state.tables), b0, step0)
    tables = sparse_jit(tables, b0, step0)
    routes = route_jit(b0)
    jax.block_until_ready(jax.tree.leaves(tables)[0])
    jax.block_until_ready(jax.tree.leaves(routes)[0])
    for i in range(8):
        b = batches[i % len(batches)]
        with prof.phase("route"):
            routes = route_jit(b)
            jax.block_until_ready(jax.tree.leaves(routes)[0])
        with prof.phase("lookup"):
            tables = lookup_jit(tables, b, step0)
            jax.block_until_ready(jax.tree.leaves(tables)[0])
        with prof.phase("lookup_plus_apply"):
            tables = sparse_jit(tables, b, step0)
            jax.block_until_ready(jax.tree.leaves(tables)[0])
    rep = prof.phase_report()
    rep["derived_sparse_apply_ms"] = round(
        rep["lookup_plus_apply"]["min_ms"] - rep["lookup"]["min_ms"], 3
    )
    rep["derived_dense_plus_overhead_ms"] = round(
        rep["step"]["min_ms"] - rep["lookup_plus_apply"]["min_ms"], 3
    )
    return rep


def _pipeline_report(trainer, batches, B, k_curve, K, pipeline_arg, smoke):
    """In-step pipelining artifact (round 11): measure the K-step scan
    under each `pipeline_mode` on the identical protocol (`_measure_k` per
    arm; the "off" arm is the already-measured k_curve entry), time the
    hoistable routing phase standalone, and put the measured pipelined
    step next to the overlap model (`ops/traffic.py
    modeled_overlap_step`: exchange time max'd with — not added to —
    dense time).  `tools/roofline.py --assert-overlap <json>` gates CI on
    this section: the pipelined arms must not regress past tolerance and
    the overlap efficiency (modeled / measured) must be recorded."""
    from deeprec_tpu.ops import traffic as T
    from deeprec_tpu.training import Trainer

    chunks = 4
    reps = 2 if smoke else 3
    timed_steps = 8 if smoke else int(os.environ.get("BENCH_TIMED_STEPS", "32"))
    # "grid" = off + lookahead. The chunked arm only differs on SHARDED
    # exchanges (ShardedTable.exchange_chunks); on this single-device
    # protocol it compiles the identical program, so the grid skips it —
    # tools/bench_async.py --pipeline-mode chunked is the mesh measurement.
    # An explicit --pipeline-mode chunked still measures it here on request.
    modes = ["off", "lookahead"]
    if pipeline_arg in ("lookahead", "chunked"):
        modes = ["off", pipeline_arg]

    # Pipelining only engages on the K-step scan; measure every arm at the
    # same K >= 2 (the already-measured k_curve entry serves the "off" arm
    # when it matches).
    K_pipe = max(K, 2)
    grid = {}
    for mode in modes:
        if mode == "off" and str(K_pipe) in k_curve:
            head = k_curve[str(K_pipe)]
            # NB: no steady_compiles here — this arm REUSES the k_curve
            # measurement, whose compile count is already reported under
            # its k arm; copying it would double-count in _guard_record.
            grid[mode] = {
                "ms_per_step": head["ms_per_step"],
                "examples_per_sec": head["examples_per_sec"],
            }
            continue
        # Same model object + optimizers as the headline trainer (bundles
        # are rebuilt per trainer, so sharing the stateless model is safe)
        # — the arms can never drift from the measured protocol.
        tr = Trainer(
            trainer.model, trainer.sparse_opt, trainer.dense_opt,
            grad_averaging=trainer.grad_averaging,
            unique_budget=trainer.unique_budget, pipeline_mode=mode,
            pipeline_chunks=chunks,
        )
        stats, _ = _measure_k(tr, batches, B, K_pipe, timed_steps, reps)
        grid[mode] = {
            "ms_per_step": stats["ms_per_step"],
            "examples_per_sec": stats["examples_per_sec"],
            "steady_compiles": stats["steady_compiles"],
        }

    # Phase decomposition for the model: route (hoistable), dense
    # (overlap target), other (stays serial: value gather + embedding
    # exchange + apply + dense update). Sub-program timings come off the
    # single-step path; the off-arm K-scan step anchors the total.
    phases = _profile_phases(trainer, batches)
    route_ms = phases["route"]["min_ms"]
    dense_ms = max(
        0.0, phases["step"]["min_ms"] - phases["lookup_plus_apply"]["min_ms"]
    )
    step_off_ms = grid["off"]["ms_per_step"]
    other_ms = max(0.0, step_off_ms - dense_ms - route_ms)
    modeled = {
        mode: round(T.modeled_overlap_step(
            dense_ms=dense_ms, route_ms=route_ms, other_ms=other_ms,
            mode=mode, chunks=chunks,
        ), 3)
        for mode in grid
    }
    pipe_modes = [m for m in grid if m != "off"]
    eff = {
        m: round(modeled[m] / grid[m]["ms_per_step"], 4)
        for m in pipe_modes
        if grid[m]["ms_per_step"] > 0
    }
    report = {
        "modes": grid,
        "chunks": chunks,
        "steps_per_dispatch": K_pipe,
        "phase_ms": {
            "route": route_ms,
            "dense": round(dense_ms, 3),
            "other": round(other_ms, 3),
        },
        "modeled_ms": modeled,
        # modeled max(exchange, dense) step vs the measured pipelined step:
        # 1.0 = the overlap the model promises fully materialized; CPU runs
        # (no async collectives) sit below it by construction.
        "overlap_efficiency": eff,
        "modeled_buffer_bytes": round(T.dlrm_reference_traffic(
            pipeline_mode="lookahead",
        )["pipeline_buffer_bytes"]),
    }
    return report, phases


def _obs_overhead_report(trainer, batches, B, smoke):
    """The telemetry-plane cost artifact (JSON 'obs_overhead', gated by
    tools/roofline.py --assert-obs): two measured single-step arms — the
    TrainLoop per-step instrumentation (one counter inc + gauge set)
    with the obs plane ON vs DEEPREC_OBS=off (no-op singletons) — plus a
    deterministic per-record microbench. `overhead_pct` (the gated
    number) is MODELED from the per-record cost × ops/step over the
    measured step time: two same-program wall-clock arms differ by
    scheduler noise that can exceed any honest overhead bound on a
    shared CI box, while the per-op cost is stable to measure; the raw
    arm timings are recorded alongside for eyeballs. A parse check of
    the live registry's Prometheus rendering rides along."""
    import time as _time

    import jax

    from deeprec_tpu.obs import metrics as om

    n = len(batches)
    steps = 6 if smoke else 16
    reps = 3

    def arm(enabled):
        om.set_metrics_enabled(enabled)
        try:
            reg = om.MetricsRegistry()
            ctr = reg.counter("bench_obs_steps", "bench arm counter")
            gau = reg.gauge("bench_obs_step", "bench arm gauge")
            state = trainer.init(0)
            for i in range(4):  # warm (programs already compiled)
                state, mets = trainer.train_step(state, batches[i % n])
            jax.block_until_ready(mets["loss"])
            times = []
            for _ in range(reps):
                t0 = _time.perf_counter()
                for i in range(steps):
                    state, mets = trainer.train_step(state, batches[i % n])
                    ctr.inc()
                    gau.set(i)
                jax.block_until_ready(mets["loss"])
                times.append(_time.perf_counter() - t0)
            return round(min(times) / steps * 1e3, 4), reg
        finally:
            om.set_metrics_enabled(None)

    on_ms, live_reg = arm(True)
    off_ms, _ = arm(False)

    # Deterministic per-record cost: counter+gauge+histogram round-robin.
    reg = om.MetricsRegistry()
    c = reg.counter("bench_obs_c", "")
    g = reg.gauge("bench_obs_g", "")
    h = reg.histogram("bench_obs_h", "")
    N = 2000 if smoke else 20000
    t0 = _time.perf_counter()
    for i in range(N):
        c.inc()
        g.set(float(i))
        h.record(1e-3)
    per_record_ns = (_time.perf_counter() - t0) / (3 * N) * 1e9
    ops_per_step = 2.0  # TrainLoop: 1 counter inc/step + save-cadence gauges
    modeled_pct = 100.0 * ops_per_step * per_record_ns / (on_ms * 1e6)

    text = live_reg.render_prometheus()
    try:
        series = len(om.parse_prometheus(text))
        parsed = True
    except ValueError:
        series, parsed = 0, False
    return {
        "arms": {"on": {"ms_per_step": on_ms},
                 "off": {"ms_per_step": off_ms}},
        "measured_overhead_pct": round(max(0.0, on_ms / off_ms - 1) * 100, 3),
        "per_record_ns": round(per_record_ns, 1),
        "ops_per_step": ops_per_step,
        "overhead_pct": round(modeled_pct, 5),
        "metrics_parse": {"parsed": parsed, "series": series},
    }


def workload():
    """The measured DLRM loop. Runs on whatever platform jax resolves."""
    import jax
    import jax.numpy as jnp

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import DLRM
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    K = max(1, int(os.environ.get("BENCH_K", "16")))
    reps = max(3, int(os.environ.get("BENCH_REPS", "3")))
    timed_steps = int(os.environ.get("BENCH_TIMED_STEPS", "32"))
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    ks = [k for k in (1, 4, 16) if k <= K]
    if K not in ks:
        ks.append(K)
    if smoke:
        timed_steps = min(timed_steps, 8)
        ks = sorted({ks[0], ks[-1]})  # endpoints only: fast CI green

    B = 2048
    # Hash dedup engine (ops/dedup.py): "auto" (default) measures each
    # table's unique fraction during pre-fill and sizes every downstream op
    # at the derived budget; an int fixes the budget; "off" keeps the
    # legacy full-batch sort-unique.
    budget_mode = os.environ.get("BENCH_UNIQUE_BUDGET", "auto")
    unique_budget = (
        None if budget_mode == "off"
        else ("auto" if budget_mode == "auto" else int(budget_mode))
    )
    model = DLRM(emb_dim=16, capacity=1 << 20)
    trainer = Trainer(model, Adagrad(lr=0.05), unique_budget=unique_budget)

    gen = SyntheticCriteo(batch_size=B, vocab=1_000_000, seed=0)
    # Pre-generate host batches so input generation isn't measured.
    batches = [
        {k: jnp.asarray(v) for k, v in gen.batch().items()} for _ in range(8)
    ]

    k_curve = {}
    dedup_stats = {}
    for k in ks:
        k_curve[str(k)], dedup_stats = _measure_k(
            trainer, batches, B, k, timed_steps, reps
        )

    head = k_curve[str(K)]
    ex_per_sec = head["examples_per_sec"]

    # Steady-state compile accounting (analysis/trace_guard.py): every
    # timed arm records how many XLA compiles landed inside its timed
    # windows — the contract is ZERO after warmup. Gated in CI by
    # tools/roofline.py --assert-compiles (and hard-enforced in smoke by
    # the guard itself).
    def _guard_record(arms: dict) -> dict:
        per_arm = {
            name: stats["steady_compiles"]
            for name, stats in arms.items()
            if isinstance(stats, dict) and "steady_compiles" in stats
        }
        return {
            "budget": 0,
            "steady_state_compiles": sum(per_arm.values()),
            "per_arm": per_arm,
        }

    traffic = _traffic_report(trainer, budget_mode, dedup_stats)
    obs_overhead = _obs_overhead_report(trainer, batches, B, smoke)
    ckpt = _ckpt_report()
    # Overlapped tier paging arm (round 20): rotated-zipf demotion stream,
    # paging off vs on — fresh-init (state-loss) rate, fold bytes, stalls,
    # step parity. Gated in CI by tools/roofline.py --assert-tier.
    tier_paging = (
        _tier_paging_report()
        if os.environ.get("BENCH_TIER", "off") != "off"
        else None
    )
    # In-step pipelining grid: measured off/lookahead(/chunked) arms +
    # the overlap model + overlap efficiency (round 11). "off" skips it.
    pipeline_arg = os.environ.get("BENCH_PIPELINE", "grid")
    pipeline, pipe_phases = (
        _pipeline_report(trainer, batches, B, k_curve, K, pipeline_arg, smoke)
        if pipeline_arg != "off"
        else (None, None)
    )
    # Skew-aware placement arm (round 12): measured per-shard exchange
    # imbalance uniform-hash vs ShardPlan on the 8-shard skewed multi-table
    # workload (own subprocess — needs the virtual mesh). Gated in CI by
    # tools/roofline.py --assert-imbalance.
    placement = (
        _run_placement_worker()
        if os.environ.get("BENCH_PLACEMENT", "off") != "off"
        else None
    )
    # Pod-scale 2-D mesh arm (round 19): flat 1-D a2a vs the two-tier
    # hierarchical exchange (+ nested lookahead) with the per-tier wire
    # model (own subprocess — needs the virtual mesh). Gated in CI by
    # tools/roofline.py --assert-hierarchy.
    mesh_rec = (
        _run_mesh_worker()
        if os.environ.get("BENCH_MESH", "off") != "off"
        else None
    )
    # --profile reuses the phase breakdown the pipeline report already
    # measured instead of running the (multi-second) protocol twice.
    phases = (
        (pipe_phases or _profile_phases(trainer, batches))
        if os.environ.get("BENCH_PROFILE") == "1"
        else None
    )

    # Record the program actually measured — backend, storage layout, and
    # kernel-trust flags — so round-over-round numbers are comparable (the
    # r03->r04 regression was an unrecorded layout change). The layout is
    # read off the measured model's own table configs, not a hardcoded
    # probe shape.
    from deeprec_tpu.embedding.table import EmbeddingTable
    from deeprec_tpu.features import table_configs
    from deeprec_tpu.ops import fused_lookup as _fl

    packs = {
        EmbeddingTable(c).pack()
        for c in table_configs(model.features).values()
    }
    pack = max(packs) if packs else 1
    print(
        json.dumps(
            {
                "metric": "dlrm_criteo_examples_per_sec",
                "value": round(ex_per_sec, 1),
                "unit": "examples/sec",
                "vs_baseline": round(ex_per_sec / BASELINE_EXAMPLES_PER_SEC, 4),
                "steps_per_dispatch": K,
                "repetitions": {
                    "mean": head["mean"], "min": head["min"],
                    "max": head["max"], "n": head["reps"],
                },
                "k_curve": k_curve,
                "device": jax.devices()[0].platform,
                "backend": jax.default_backend(),
                "layout": "packed_x%d" % pack if pack > 1 else "unpacked",
                # Dedup engine telemetry: per-table measured unique fraction
                # + budget-overflowed ids from the timed windows, and the
                # budget mode the run used (comparability across rounds).
                "unique_budget": budget_mode,
                "dedup": dedup_stats,
                # Steady-state retrace gate: compiles observed inside the
                # timed windows of every arm (contract: 0 after warmup) —
                # checked by tools/roofline.py --assert-compiles.
                "trace_guard": _guard_record({
                    **{f"k{kk}": st for kk, st in k_curve.items()},
                    **({f"pipeline_{m}": st
                        for m, st in pipeline["modes"].items()}
                       if pipeline else {}),
                }),
                # Traffic-diet artifact: modeled engine bytes/step (before
                # vs after, measured + reference sharded shapes) and the
                # MEASURED gather/scatter op counts of the hot path, which
                # tools/roofline.py --assert-traffic checks against the
                # model (ops/traffic.py).
                "traffic": traffic,
                # Telemetry-plane cost (round 13): instrumented vs
                # DEEPREC_OBS=off step arms + deterministic per-record
                # cost; tools/roofline.py --assert-obs gates the modeled
                # overhead ≤2% and the /metrics parse check.
                "obs_overhead": obs_overhead,
                # Host-choreography stall accounting (round 9): training-
                # thread ms per checkpoint / tier sync (sync vs async) and
                # the incremental-save transfer diet (dirty-compacted vs
                # full-table device->host bytes).
                "ckpt": ckpt,
                # Overlapped tier paging (round 20): rotated-zipf paging
                # off/on arms — fresh-init (optimizer-state-loss) rate,
                # fold bytes/stall, step parity, steady fold compiles —
                # gated by tools/roofline.py --assert-tier in CI smoke.
                **({"tier_paging": tier_paging} if tier_paging else {}),
                # In-step pipelining (round 11): per-mode K-scan step time,
                # phase decomposition (route / dense / other), the overlap
                # model and its efficiency vs measurement — gated by
                # tools/roofline.py --assert-overlap in CI smoke.
                **({"pipeline": pipeline} if pipeline else {}),
                # Skew-aware placement (round 12): measured per-shard
                # exchange-bytes imbalance before (uniform hash) and after
                # (adopted ShardPlan) + step time per arm — gated by
                # tools/roofline.py --assert-imbalance in CI smoke.
                **({"placement": placement} if placement else {}),
                # Pod-scale 2-D mesh (round 19): per-tier modeled wire
                # bytes of the hierarchical exchange vs flat a2a on the
                # same topology, bitwise loss parity across arms, 0
                # overflow / steady compiles, nested K-scan — gated by
                # tools/roofline.py --assert-hierarchy in CI smoke.
                **({"mesh": mesh_rec} if mesh_rec else {}),
                **({"phases": phases} if phases else {}),
                "flags": {
                    "f32_row": _fl.AUTO_TRUSTS_F32_ROW,
                    "bf16_pair": _fl.AUTO_TRUSTS_BF16_PAIR,
                },
            }
        )
    )


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps-per-dispatch", type=int,
                   default=int(os.environ.get("BENCH_K", "16")),
                   help="K training steps per device dispatch (lax.scan); "
                        "the K-curve over {1,4,16} up to K is also measured")
    p.add_argument("--reps", type=int,
                   default=int(os.environ.get("BENCH_REPS", "3")),
                   help="timed repetitions per K (min 3; JSON records "
                        "mean/min/max so noise is visible)")
    p.add_argument("--timed-steps", type=int,
                   default=int(os.environ.get("BENCH_TIMED_STEPS", "32")),
                   help="training steps per timed repetition")
    p.add_argument("--smoke", action="store_true",
                   help="fast CI path: endpoints-only K sweep, short windows")
    p.add_argument("--unique-budget",
                   default=os.environ.get("BENCH_UNIQUE_BUDGET", "auto"),
                   help="hash dedup unique budget: 'auto' (measured EMA, "
                        "default), an int (fixed ids per lookup), or 'off' "
                        "(legacy full-batch sort-unique)")
    p.add_argument("--pipeline-mode",
                   default=os.environ.get("BENCH_PIPELINE", "grid"),
                   choices=["off", "lookahead", "chunked", "grid"],
                   help="in-step pipelining arms to measure on the K-step "
                        "scan: 'grid' (default) records off + lookahead "
                        "with the overlap model under JSON 'pipeline' "
                        "(chunked only differs on sharded exchanges — see "
                        "tools/bench_async.py); a single mode measures "
                        "just off + that arm; 'off' skips the section")
    p.add_argument("--placement", nargs="?", const="grid",
                   default=os.environ.get("BENCH_PLACEMENT", "off"),
                   choices=["off", "uniform", "plan", "grid"],
                   help="skew-aware placement arm on the 8-shard skewed "
                        "multi-table workload (own subprocess): 'grid' "
                        "(bare --placement) measures uniform-hash AND the "
                        "adopted ShardPlan (imbalance before/after + step "
                        "time, JSON 'placement'); 'uniform' measures only "
                        "the hash baseline; 'plan' is an alias of grid "
                        "(the plan arm needs the uniform window first); "
                        "'off' (default) skips the section")
    p.add_argument("--mesh", nargs="?", const="grid",
                   default=os.environ.get("BENCH_MESH", "off"),
                   choices=["off", "1d", "2d", "grid"],
                   help="pod-scale 2-D mesh arm on the virtual 8-device "
                        "mesh (own subprocess): 'grid' (bare --mesh) "
                        "measures flat 1-D a2a AND the 2x4 hierarchical "
                        "two-tier exchange (+ nested lookahead K-scan) "
                        "with the per-tier wire model (JSON 'mesh'); "
                        "'1d'/'2d' run one side; 'off' (default) skips")
    p.add_argument("--tier-paging", action="store_true",
                   default=os.environ.get("BENCH_TIER", "off") != "off",
                   help="add the overlapped tier paging arm: rotated-zipf "
                        "stream forcing demotion mid-window, paging off vs "
                        "on — fresh-init (state-loss) rate, fold bytes, "
                        "training-thread stall and step-time parity (JSON "
                        "'tier_paging'); gated by roofline --assert-tier")
    p.add_argument("--profile", action="store_true",
                   help="add a per-phase step breakdown (lookup / sparse "
                        "apply / dense+overhead, training/profiler.py) to "
                        "the JSON")
    args = p.parse_args()
    if args.steps_per_dispatch < 1:
        p.error("--steps-per-dispatch must be >= 1")
    if args.unique_budget not in ("auto", "off"):
        try:
            if int(args.unique_budget) <= 0:
                raise ValueError
        except ValueError:
            p.error("--unique-budget must be 'auto', 'off' or a positive int")
    # The measured workload runs in a subprocess; parameters ride the env.
    os.environ["BENCH_K"] = str(args.steps_per_dispatch)
    os.environ["BENCH_REPS"] = str(args.reps)
    os.environ["BENCH_TIMED_STEPS"] = str(args.timed_steps)
    os.environ["BENCH_UNIQUE_BUDGET"] = str(args.unique_budget)
    os.environ["BENCH_PIPELINE"] = str(args.pipeline_mode)
    os.environ["BENCH_PLACEMENT"] = str(args.placement)
    os.environ["BENCH_MESH"] = str(args.mesh)
    os.environ["BENCH_TIER"] = "on" if args.tier_paging else "off"
    if args.profile:
        os.environ["BENCH_PROFILE"] = "1"
    if args.smoke:
        os.environ["BENCH_SMOKE"] = "1"
    if os.environ.get("BENCH_FORCED") == "1":
        # CI / smoke path: skip the (many-minute) probe window and measure
        # on whatever platform jax resolves in this environment.
        workload()
        return
    ok, probe_diag = _probe_with_retry()
    result, err = None, None
    if ok:
        # Pin the platform: if the tunnel drops between probe and worker,
        # jax must fail loudly (rc!=0 -> clean CPU fallback), not silently
        # init on CPU and mislabel a CPU number as a TPU measurement.
        result, err = _run_worker(
            {"JAX_PLATFORMS": "tpu"},
            timeout=int(os.environ.get("BENCH_TPU_TIMEOUT", "900")))
        if result is not None and result.get("device") != "tpu":
            result, err = None, "worker ran on %s" % result.get("device")
        if result is None:
            probe_diag = "came up then failed: " + err
            sys.stderr.write("bench: TPU workload failed (%s), falling back to CPU\n" % err)
    if result is None:
        sys.stderr.write("bench: TPU %s, falling back to CPU\n" % probe_diag)
        result, err = _run_worker(
            {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""}, timeout=1800)
    if result is None:
        result = {
            "metric": "dlrm_criteo_examples_per_sec", "value": 0.0,
            "unit": "examples/sec", "vs_baseline": 0.0,
            "device": "none", "error": err,
        }
    result["tpu"] = probe_diag
    print(json.dumps(result))


if __name__ == "__main__":
    if os.environ.get("BENCH_PLACEMENT_WORKER") == "1":
        _placement_workload()
    elif os.environ.get("BENCH_MESH_WORKER") == "1":
        _mesh_workload()
    elif os.environ.get("BENCH_WORKER") == "1":
        workload()
    else:
        main()
