"""Benchmark harness — prints ONE JSON line.

Metric: DLRM synthetic-Criteo training throughput (examples/sec) on the
available device, batch 2048, reference protocol mean(steps/sec) × batch
(modelzoo/benchmark/*/README.md). vs_baseline compares against the
reference's best published DLRM number: 188.11 global steps/sec × bs 2048 =
385,249 examples/sec on 1×A100-80G + 64-core Xeon
(docs/docs_en/Smart-Stage.md:182-190, see BASELINE.md).
"""
import json
import os
import subprocess
import sys
import time

BASELINE_EXAMPLES_PER_SEC = 188.11 * 2048  # DLRM GPU SmartStage, BASELINE.md


def _tpu_alive(timeout: int = 90) -> bool:
    """Probe the TPU in a subprocess so a wedged tunnel can't hang the
    benchmark itself."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp;"
             "x = jnp.ones((256, 256));"
             "print((x @ x).sum())"],
            timeout=timeout, capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    if os.environ.get("BENCH_FORCED") != "1" and not _tpu_alive():
        # TPU unreachable: rerun self on CPU so the harness still gets its
        # JSON line (the value then reflects CPU, not TPU, throughput).
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": "", "BENCH_FORCED": "1"})
        sys.stderr.write("bench: TPU unreachable, falling back to CPU\n")
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)
    import jax
    import jax.numpy as jnp

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import DLRM
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    B = 2048
    model = DLRM(emb_dim=16, capacity=1 << 20)
    trainer = Trainer(model, Adagrad(lr=0.05))
    state = trainer.init(0)
    gen = SyntheticCriteo(batch_size=B, vocab=1_000_000, seed=0)

    # Pre-generate host batches so input generation isn't measured.
    batches = [
        {k: jnp.asarray(v) for k, v in gen.batch().items()} for _ in range(8)
    ]

    # Warmup (compile + table fill).
    for i in range(3):
        state, mets = trainer.train_step(state, batches[i % len(batches)])
    jax.block_until_ready(mets["loss"])

    # Best of 3 windows: the tunnel-attached TPU shows ±15% run-to-run
    # noise on identical programs; the fastest window is the least-noisy
    # estimate of the program's actual step time.
    steps = 30
    best_dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(steps):
            state, mets = trainer.train_step(state, batches[i % len(batches)])
        jax.block_until_ready(mets["loss"])
        best_dt = min(best_dt, time.perf_counter() - t0)

    ex_per_sec = steps * B / best_dt
    print(
        json.dumps(
            {
                "metric": "dlrm_criteo_examples_per_sec",
                "value": round(ex_per_sec, 1),
                "unit": "examples/sec",
                "vs_baseline": round(ex_per_sec / BASELINE_EXAMPLES_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
