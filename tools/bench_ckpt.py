"""Microbenchmark: checkpoint / multi-tier choreography stalls, sync vs async.

Measures what the round-9 off-the-hot-path work actually moved off the
training thread (`training/checkpoint.py`, `embedding/multi_tier.py`):

  * save rows[] — per (capacity, dirty_fraction): the training-thread
    stall of an incremental save on the synchronous path vs the async
    writer (stage-only), the background writer's own write time, and the
    device->host transfer bytes of the dirty-compacted export next to the
    full-table bytes the legacy exporter pulled (`compaction_reduction`
    is the diet; it should track 1 - dirty_fraction up to pow2 padding
    and the [C] key array).
  * full_save — sync-vs-async stall for a full checkpoint (the async win
    here is the npz IO, not the transfer: full saves move every row).
  * tier — MultiTierTable.sync() vs sync_async(): caller-side stall of a
    demotion burst (the sync path pulls full [C, D] values + slots to the
    host; the async path gathers the demoted rows on device and hands the
    HostKV IO to a background round).

Prints ONE JSON line (the bench.py convention). `--smoke` shrinks the grid
so CI merely proves both paths work (cibuild/run_tests.sh).
"""
import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bench_save(capacity, dirty_frac, reps):
    import jax
    import jax.numpy as jnp

    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = WDL(emb_dim=16, capacity=capacity, hidden=(32,), num_cat=4,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1))
    st = tr.init(0)
    rng = np.random.default_rng(0)
    fill = int(capacity * 0.5)

    def batch(n_ids, seed_ids):
        ids = seed_ids.astype(np.int32)
        b = {f"C{i+1}": jnp.asarray(ids) for i in range(4)}
        b["I1"] = jnp.asarray(rng.standard_normal((n_ids, 1)).astype(np.float32))
        b["I2"] = jnp.asarray(rng.standard_normal((n_ids, 1)).astype(np.float32))
        b["label"] = jnp.asarray((rng.random(n_ids) < 0.5).astype(np.float32))
        return b

    # fill ~half the table, take a full save so dirty bits clear
    st, mets = tr.train_step(st, batch(fill, np.arange(fill)))
    jax.block_until_ready(mets["loss"])
    tmp = tempfile.mkdtemp(prefix="deeprec_bench_ckpt_")
    try:
        out = {"capacity": capacity, "dirty_fraction": dirty_frac}
        ck = CheckpointManager(os.path.join(tmp, "s"), tr)
        st, _ = ck.save(st)
        full_bytes = ck.last_save["transfer_bytes"]

        sync_ms, async_ms, write_ms, incr_bytes = [], [], [], 0
        for r in range(reps):
            n_dirty = max(1, int(fill * dirty_frac))
            ids = rng.choice(fill, size=n_dirty, replace=False)
            st, mets = tr.train_step(st, batch(n_dirty, ids))
            jax.block_until_ready(mets["loss"])
            st_s, _ = ck.save_incremental(st)
            sync_ms.append(ck.last_save["stall_ms"])
            incr_bytes = ck.last_save["transfer_bytes"]
            # async from the SAME pre-clear state: identical delta
            cka = CheckpointManager(os.path.join(tmp, f"a{r}"), tr)
            st, _ = cka.save_incremental_async(st)
            async_ms.append(cka.last_save["stall_ms"])
            cka.wait()
            write_ms.append(cka.last_save.get("write_ms", 0.0))
            st = st_s  # keep ONE cleared lineage so deltas stay comparable
        out.update(
            sync_stall_ms=round(min(sync_ms), 3),
            async_stall_ms=round(min(async_ms), 3),
            writer_ms=round(min(write_ms), 3),
            incr_transfer_bytes=int(incr_bytes),
            full_transfer_bytes=int(full_bytes),
            compaction_reduction=round(1.0 - incr_bytes / full_bytes, 4),
        )
        # full save, both ways
        t0 = time.perf_counter()
        st, _ = ck.save(st)
        out["full_sync_stall_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        ckf = CheckpointManager(os.path.join(tmp, "af"), tr)
        t0 = time.perf_counter()
        st, _ = ckf.save_async(st)
        out["full_async_stall_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        ckf.wait()
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_tier(capacity, reps):
    import jax
    import jax.numpy as jnp

    from deeprec_tpu.config import (
        EmbeddingVariableOption, StorageOption, TableConfig,
    )
    from deeprec_tpu.embedding.multi_tier import MultiTierTable
    from deeprec_tpu.embedding.table import EmbeddingTable

    def run(use_async):
        best = float("inf")
        for _ in range(reps):
            cfg = TableConfig(
                name="bench_tier", dim=16, capacity=capacity,
                ev=EmbeddingVariableOption(storage=StorageOption(
                    storage_type="hbm_dram")),
            )
            t = EmbeddingTable(cfg)
            mt = MultiTierTable(t, high_watermark=0.7, low_watermark=0.5)
            s = t.create()
            s, res = t.lookup_unique(
                s, jnp.arange(int(capacity * 0.85), dtype=jnp.int32), step=0
            )
            jax.block_until_ready(res.embeddings)
            t0 = time.perf_counter()
            s, stats = (mt.sync_async(s, 1) if use_async else mt.sync(s, 1))
            best = min(best, (time.perf_counter() - t0) * 1e3)
            assert stats.demoted > 0
            if use_async:
                mt.drain(s)
        return round(best, 3)

    return {
        "capacity": capacity,
        "sync_stall_ms": run(False),
        "async_stall_ms": run(True),
    }


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="fast CI grid: one shape, one rep")
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()
    reps = 1 if args.smoke else max(1, args.reps)
    caps = [1 << 13] if args.smoke else [1 << 14, 1 << 16]
    fracs = [0.05] if args.smoke else [0.01, 0.05, 0.25]

    rows = [
        _bench_save(c, f, reps) for c in caps for f in fracs
    ]
    tier = _bench_tier(caps[0], reps)
    import jax

    print(json.dumps({
        "metric": "ckpt_choreography_stall_ms",
        "device": jax.devices()[0].platform,
        "save": rows,
        "tier": tier,
    }))


if __name__ == "__main__":
    main()
