"""Full-corpus retrieval bench: blocked top-k over the resident
quantized item matrix (serving/retrieval.py + ops/topk.py).

Measures the whole retrieval contract end to end and records it as the
`retrieval` section of RETRIEVAL_BENCH.json, gated in CI by
`roofline.py --assert-retrieval`:

  * **qps × corpus size** — user queries/sec through the coalesced
    sweep at 1M–10M items, int8 vs fp32 residency arms (plus corpus
    build time: ingest + fixed-chunk encode).
  * **recall@k vs exact scoring** — the int8 blocked sweep against an
    exact fp32 full-scan argsort over the same item vectors.
  * **block-size curve** — sweep qps across block_rows settings.
  * **sweep vs per-row gather** — the resident sweep against the
    pointwise baseline that re-gathers item rows and re-runs the item
    tower per query (what serving full-corpus scoring costs WITHOUT the
    resident matrix); the gate pins the sweep ≥ 3× at the 1M smoke
    shape.
  * **freshness** — a delta checkpoint lands under a live poller; the
    lag from trainer commit to the corpus fold that makes the changed
    items retrievable, against the predictor's own pinned
    train_to_serve lag (gate: retrievable ≤ 2× pinned).
  * **residency + compiles** — measured-vs-modeled sweep bytes
    (ops/traffic.py retrieval_sweep_bytes, exact equality) and zero
    steady-state XLA compiles across delta replay folding into the
    corpus (trace-guard, the PR 5 contract).

Run:  python tools/bench_retrieval.py [--corpus 1000000,10000000]
          [--seconds 3] [--k 100] [--out RETRIEVAL_BENCH.json]
`--smoke` runs the 1M-item shape of every arm with short windows and
asserts structure (CI; the numeric gates live in roofline.py).

On a TPU host run WITHOUT JAX_PLATFORMS=cpu to sweep from the chip.
"""
import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Two-tower stimulus: asymmetric (heavy user tower, cheap item
# projection — the regime where one user pass amortizing over the whole
# corpus pays), item vocab sized so item-feature combinations cover 10M
# distinct items.
MODEL_ARGS = dict(emb_dim=16, capacity=1 << 16, num_user_feats=4,
                  num_item_feats=2, hidden=(32, 16),
                  user_hidden=(256, 64, 16))
VOCAB = 4096
ZIPF_A = 1.2
# Raw item-id band reserved for the freshness arm: the bulk corpus
# never uses these ids, so a delta that trains them dirties ONLY the
# freshly ingested probe items — the fold is the targeted ingest->
# retrievable path, not a full re-encode.
FRESH_BAND = 64


def build(tmp, steps=8):
    import jax.numpy as jnp
    import optax

    from deeprec_tpu.data import SyntheticTwoTower
    from deeprec_tpu.models import DSSM
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = DSSM(**MODEL_ARGS)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(
        batch_size=512, num_user=MODEL_ARGS["num_user_feats"],
        num_item=MODEL_ARGS["num_item_feats"], vocab=VOCAB,
        zipf_a=ZIPF_A, seed=17)
    for _ in range(steps):
        st, _ = tr.train_step(st, {k: jnp.asarray(v)
                                   for k, v in gen.batch().items()})
    ck = CheckpointManager(tmp, tr)
    st, _ = ck.save(st)
    rng = np.random.default_rng(99)

    # Online phase: sparse-only updates (embeddings train, towers
    # frozen) — the regime where the targeted corpus fold is sound; a
    # delta that moved the dense item tower escalates the fold to a full
    # re-encode (serving/retrieval.py dense fingerprint), which is the
    # full-retrain -> full-reload path, not the online steady state.
    from deeprec_tpu.training.trainer import TrainState

    tr2 = Trainer(model, Adagrad(lr=0.1), optax.set_to_zero())
    st = TrainState(step=st.step, tables=st.tables, dense=st.dense,
                    opt_state=tr2.dense_opt.init(st.dense))
    ck2 = CheckpointManager(tmp, tr2)

    def save_delta(targeted=False):
        """Train 2 sparse-only steps and land an incremental checkpoint.
        `targeted` confines the steps' ITEM ids to the reserved
        freshness band, so the delta's item-table keys touch only the
        probe items."""
        nonlocal st
        for _ in range(2):
            b = gen.batch()
            if targeted:
                for i in range(MODEL_ARGS["num_item_feats"]):
                    raw = rng.integers(VOCAB - FRESH_BAND, VOCAB,
                                       size=len(b["label"]))
                    b[f"V{i}"] = ((i + 1) * VOCAB + raw).astype(np.int32)
            st, _ = tr2.train_step(st, {k: jnp.asarray(v)
                                        for k, v in b.items()})
        st, _ = ck2.save_incremental(st)

    save_delta()  # prime trainer-side incremental-save programs
    return model, gen, save_delta


def make_items(n, seed=0):
    """Corpus: n distinct items whose feature ids follow the TRAINED
    zipf distribution (head items carry learned vectors, the long tail
    rides initializer/default rows — the production shape)."""
    from deeprec_tpu.data.synthetic import zipf_ids

    rng = np.random.default_rng(seed)
    ids = np.arange(1, n + 1, dtype=np.int64)
    feats = {}
    for i in range(MODEL_ARGS["num_item_feats"]):
        raw = np.minimum(zipf_ids(rng, VOCAB, ZIPF_A, (n,)),
                         VOCAB - FRESH_BAND - 1)  # keep the band free
        feats[f"V{i}"] = (raw + (i + 1) * VOCAB).astype(np.int32)
    return ids, feats


def make_user_batch(pred, gen, rows):
    from deeprec_tpu.serving.predictor import parse_features
    from deeprec_tpu.serving.retrieval import fill_missing_item_features

    b = gen.batch()
    user = {k: np.asarray(v)[:rows] for k, v in b.items()
            if k.startswith("U")}
    return parse_features(pred, fill_missing_item_features(pred, user))


def measure_qps(engine, batch, k, seconds):
    """Closed-loop sweep rate: queries (user rows)/sec and sweeps/sec."""
    engine.retrieve(batch, k)  # warm the bucket outside the window
    rows = len(next(iter(batch.values())))
    sweeps = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        engine.retrieve(batch, k)
        sweeps += 1
    dt = time.perf_counter() - t0
    return {"qps": round(rows * sweeps / dt, 2),
            "sweeps_per_sec": round(sweeps / dt, 3),
            "rows_per_sweep": rows}


def gather_baseline(pred, engine_fp32, batch, k, reps=2):
    """The per-row-gather full-corpus baseline: no resident matrix —
    every query re-gathers the item rows and re-runs the item tower over
    the WHOLE corpus in fixed chunks (the engine's own encode program,
    so the comparison is tower-for-tower honest), then scores + merges
    host-side. This is what pointwise serving would pay to score the
    catalog; the resident blocked sweep exists to beat it."""
    import jax.numpy as jnp

    eng = engine_fp32
    state = pred._snap.state
    jb = {kk: jnp.asarray(v) for kk, v in batch.items()}
    uvec = np.asarray(eng._user_jit(state, jb))
    rows = uvec.shape[0]
    n = eng.corpus_rows()
    t0 = time.perf_counter()
    for _ in range(reps):
        best_v = np.full((rows, k), -np.inf, np.float32)
        best_i = np.full((rows, k), -1, np.int64)
        for off in range(0, n, eng.chunk):
            sl = np.arange(off, min(off + eng.chunk, n))
            ix = np.zeros((eng.chunk,), np.int64)
            ix[:sl.size] = sl
            chunk_batch = {}
            for name, tmpl in eng._templates.items():
                col = (eng._h_feats[name][ix] if name in eng._h_feats
                       else np.repeat(tmpl, eng.chunk, axis=0))
                chunk_batch[name] = jnp.asarray(col)
            vecs, _ = eng._encode_jit(state, chunk_batch)
            scores = uvec @ np.asarray(vecs).T[:, :sl.size]
            allv = np.concatenate([best_v, scores], axis=1)
            alli = np.concatenate(
                [best_i, np.broadcast_to(eng._h_ids[sl], scores.shape)],
                axis=1)
            top = np.argpartition(-allv, k - 1, axis=1)[:, :k]
            best_v = np.take_along_axis(allv, top, axis=1)
            best_i = np.take_along_axis(alli, top, axis=1)
    dt = (time.perf_counter() - t0) / reps
    return {"gather_qps": round(rows / dt, 3),
            "seconds_per_query_batch": round(dt, 3)}


def recall_arm(pred, eng8, eng32, gen, queries, k_list):
    """int8 blocked sweep vs exact fp32 full-scan argsort (the fp32
    engine's item vectors ARE the exact reference — same tower, no
    quantization, no blocking). Tie-aware recall (the ANN-benchmark
    definition): a retrieved item counts as a hit when its EXACT score
    reaches the exact k-th score — items whose fp32 scores tie exactly
    (zipf-head items sharing feature values encode identical vectors)
    are interchangeable answers, not misses."""
    import jax.numpy as jnp

    batch = make_user_batch(pred, gen, queries)
    hids, hv = eng32.host_vectors()
    uvec = np.asarray(eng32._user_jit(
        pred._snap.state, {kk: jnp.asarray(v) for kk, v in batch.items()}))
    exact = uvec @ hv.T  # [Q, C] fp32 full scan
    out = {"queries": queries}
    kmax = max(k_list)
    res = eng8.retrieve(batch, kmax)
    # exact scores of the retrieved ids: hids is ascending by
    # construction (ids 1..N ingested in order), so id -> column is one
    # searchsorted
    cols = np.searchsorted(hids, res.ids)
    got_exact = np.take_along_axis(
        exact, np.clip(cols, 0, exact.shape[1] - 1), axis=1)
    got_exact = np.where(res.ids >= 0, got_exact, -np.inf)
    for k in k_list:
        kth = -np.partition(-exact, k - 1, axis=1)[:, k - 1]
        hits = got_exact[:, :k] >= kth[:, None] - 1e-6
        out[f"recall_at_{k}"] = round(float(hits.mean()), 4)
    return out


def freshness_arm(pred, engine, save_delta, poll_secs=0.2,
                  timeout=30.0):
    """The ingest->retrievable lag: ingest NEW probe items (reserved id
    band), land a delta that trains exactly those items under a live
    poll loop, and measure trainer-commit -> corpus-fold — the instant
    the probe items' trained vectors became retrievable (the fold runs
    INSIDE the same poll round that swapped the model, and it re-encodes
    only the rows the delta touched)."""
    import threading

    rng = np.random.default_rng(5)
    fresh_n = 128
    fresh_ids = np.arange(10_000_000_001, 10_000_000_001 + fresh_n,
                          dtype=np.int64)
    fresh_feats = {
        f"V{i}": ((i + 1) * VOCAB
                  + rng.integers(VOCAB - FRESH_BAND, VOCAB,
                                 size=fresh_n)).astype(np.int32)
        for i in range(MODEL_ARGS["num_item_feats"])
    }
    engine.upsert_items(fresh_ids, fresh_feats)
    folds0 = engine.folds
    stop = threading.Event()

    def poller():
        while not stop.is_set():
            try:
                pred.poll_updates()
            except Exception:
                pass
            stop.wait(poll_secs)

    th = threading.Thread(target=poller, daemon=True)
    th.start()
    try:
        t0 = time.time()
        save_delta(targeted=True)  # returns after the manifest commit
        t_commit = time.time()
        deadline = time.time() + timeout
        while time.time() < deadline:
            lf = engine.last_fold
            if engine.folds > folds0 and lf and lf["time"] >= t0:
                break
            time.sleep(0.01)
        else:
            raise RuntimeError("delta never folded into the corpus")
    finally:
        stop.set()
        th.join(timeout=2)
    lf = dict(engine.last_fold)
    pinned = pred.last_apply_lag_seconds or 0.0
    retrievable = max(0.0, lf["time"] - t_commit)
    return {
        "retrievable_seconds": round(retrievable, 4),
        "pinned_lag_seconds": round(pinned, 4),
        "fold_seconds": lf["seconds"],
        "rows_folded": lf["rows"],
        "poll_secs": poll_secs,
        "ratio": round(retrievable / max(pinned, 0.05), 3),
    }


def compile_arm(pred, engine, save_delta, batch, k):
    """Zero steady-state compiles: after one full warm cycle, a delta
    replay + corpus fold + retrieve must compile NOTHING."""
    from deeprec_tpu.analysis.trace_guard import trace_guard

    engine.retrieve(batch, k)
    save_delta(targeted=True)
    pred.poll_updates()  # first replay+fold: pads every cache
    engine.retrieve(batch, k)
    save_delta(targeted=True)
    with trace_guard(max_compiles=None) as g:
        pred.poll_updates()
        engine.retrieve(batch, k)
    return g.compiles


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--corpus", default="1000000,10000000",
                    help="comma-separated corpus sizes for the qps grid")
    ap.add_argument("--blocks", default="1024,4096,16384",
                    help="block-size curve (pow2 rows per sweep block)")
    ap.add_argument("--block-curve-corpus", type=int, default=262144,
                    help="corpus size the block curve re-ingests at")
    ap.add_argument("--k", type=int, default=100)
    ap.add_argument("--rows", type=int, default=8,
                    help="user rows per coalesced query batch")
    ap.add_argument("--recall-queries", type=int, default=32)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--chunk", type=int, default=8192,
                    help="fixed encode-chunk rows")
    ap.add_argument("--out", default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="CI pass: the 1M-item shape of every arm, short "
                         "windows, structural asserts (numeric gates in "
                         "roofline.py --assert-retrieval)")
    args = ap.parse_args()
    if args.smoke:
        args.corpus = "1000000"
        args.blocks = "4096,16384"
        args.block_curve_corpus = 131072
        args.seconds = 1.5
        args.recall_queries = 16

    from deeprec_tpu.serving import Predictor
    from deeprec_tpu.serving.retrieval import RetrievalEngine

    sizes = sorted({int(x) for x in args.corpus.split(",") if x})
    section = {
        "protocol": {"k": args.k, "rows_per_query_batch": args.rows,
                     "model": MODEL_ARGS, "vocab": VOCAB,
                     "corpus_sizes": sizes, "seconds": args.seconds},
        "backend": None, "arms": {}, "block_curve": {},
    }
    with tempfile.TemporaryDirectory() as tmp:
        model, gen, save_delta = build(tmp)
        pred = Predictor(model, tmp)
        import jax

        section["backend"] = jax.default_backend()
        batch = make_user_batch(pred, gen, args.rows)

        biggest = sizes[-1]
        eng8 = eng32 = None
        for n in sizes:
            ids, feats = make_items(n)
            arm = {}
            for quant in ("int8", "fp32"):
                t0 = time.perf_counter()
                eng = RetrievalEngine(pred, quantize=quant,
                                      chunk=args.chunk)
                eng.upsert_items(ids, feats)
                build_s = time.perf_counter() - t0
                qps = measure_qps(eng, batch, args.k, args.seconds)
                arm[quant] = {**qps, "build_s": round(build_s, 2),
                              "corpus_rows": eng.corpus_rows()}
                print(json.dumps({"config": f"corpus-{n}-{quant}",
                                  **arm[quant]}), flush=True)
                if quant == "int8":
                    eng8 = eng
                else:
                    eng32 = eng
            section["arms"][str(n)] = arm

            if n == sizes[0]:
                # recall + residency + gather baseline + freshness +
                # compile gate all run at the smallest (smoke) shape —
                # eng8/eng32 still hold this corpus.
                section["recall"] = {
                    "int8": recall_arm(pred, eng8, eng32, gen,
                                       args.recall_queries, [10, args.k])}
                print(json.dumps({"config": "recall",
                                  **section["recall"]["int8"]}),
                      flush=True)
                section["residency"] = {"int8": eng8.sweep_info(),
                                        "fp32": eng32.sweep_info()}
                gb = gather_baseline(pred, eng32, batch, args.k,
                                     reps=1 if args.smoke else 2)
                sweep_qps = section["arms"][str(n)]["int8"]["qps"]
                section["sweep_vs_gather"] = {
                    **gb, "sweep_qps": sweep_qps,
                    "corpus_rows": n,
                    "speedup": round(sweep_qps / gb["gather_qps"], 2),
                }
                print(json.dumps({"config": "sweep-vs-gather",
                                  **section["sweep_vs_gather"]}),
                      flush=True)
                # the int8 engine is the predictor's registered fold
                # target for the freshness/compile arms (the LAST
                # constructed engine holds the attachment — re-attach
                # the arm under test explicitly)
                pred.attach_retrieval(eng8)
                section["freshness"] = freshness_arm(pred, eng8,
                                                     save_delta)
                print(json.dumps({"config": "freshness",
                                  **section["freshness"]}), flush=True)
                section["steady_compiles"] = compile_arm(
                    pred, eng8, save_delta, batch, args.k)
                print(json.dumps(
                    {"config": "trace-guard",
                     "steady_compiles": section["steady_compiles"]}),
                    flush=True)
            if n != sizes[0] and n != biggest:
                del eng8, eng32  # free the mid-grid corpora

        # block-size curve: re-ingest a bounded corpus per block setting
        ids, feats = make_items(args.block_curve_corpus)
        for blk in sorted({int(x) for x in args.blocks.split(",") if x}):
            eng = RetrievalEngine(pred, quantize="int8", block_rows=blk,
                                  chunk=args.chunk)
            eng.upsert_items(ids, feats)
            qps = measure_qps(eng, batch, args.k,
                              min(args.seconds, 2.0))
            section["block_curve"][str(blk)] = {
                **qps, "corpus_rows": args.block_curve_corpus}
            print(json.dumps({"config": f"block-{blk}", **qps}),
                  flush=True)

    if args.smoke:
        check_smoke(section)
        print("bench_retrieval smoke OK", flush=True)
    out = {"retrieval": section}
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)


def check_smoke(section):
    """Structural asserts (the numeric gates live in roofline.py)."""
    assert section["arms"], section
    for n, arm in section["arms"].items():
        for quant in ("int8", "fp32"):
            assert arm[quant]["qps"] > 0, (n, quant)
            assert arm[quant]["corpus_rows"] == int(n), (n, arm)
    ri = section["residency"]
    for quant in ("int8", "fp32"):
        info = ri[quant]
        assert info["measured_bytes"] == info["modeled_bytes"], info
    assert ri["int8"]["measured_bytes"] < ri["fp32"]["measured_bytes"]
    assert "recall_at_10" in section["recall"]["int8"]
    assert section["sweep_vs_gather"]["gather_qps"] > 0
    assert section["freshness"]["rows_folded"] > 0
    assert "steady_compiles" in section
    assert section["block_curve"]


if __name__ == "__main__":
    main()
