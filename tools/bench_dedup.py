"""Microbenchmark: sort-based `jnp.unique` vs the hash dedup engine.

Measures the two dedup implementations behind the embedding hot path
(`ops/dedup.py`) at identical static output sizes, across flattened batch
size N, unique-budget ratios U/N and zipf skew — the knob space of
`TableConfig.unique_budget`. The reference shape is the DLRM bench batch:
N = 26 features x 2048 = 53,248 flattened ids, U/N = 0.25, zipf α = 1.05
(the heaviest-tail column of the CriteoStats generator).

Prints ONE JSON line (the bench.py convention):
  rows[]    — per-(N, ratio, alpha): sort_ms, hash_ms, speedup,
              true_unique_frac, overflow (ids past the budget, served the
              default by the engine's contract)
  reference — the DLRM reference-shape row, the acceptance comparison

`--smoke` shrinks the grid and the timed windows so CI merely proves both
paths compile and run (cibuild/run_tests.sh).
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bench_one(N, ratio, alpha, reps, vocab=None):
    import jax
    import jax.numpy as jnp

    from deeprec_tpu.data.synthetic import zipf_ids
    from deeprec_tpu.ops import dedup

    vocab = vocab or max(1024, N)
    rng = np.random.default_rng(7)
    ids = zipf_ids(rng, vocab, alpha, (N,)).astype(np.int32)
    sentinel = int(np.iinfo(np.int32).min)
    # ~2% padding, collapsed onto the sentinel like the lookup path does.
    flat = np.where(rng.random(N) < 0.02, sentinel, ids).astype(np.int32)
    true_unique = int(np.unique(flat[flat != sentinel]).size)
    size = dedup.resolve_size(max(1, int(N * ratio)), N)

    sort_fn = jax.jit(  # noqa: DRT001 — built once per bench invocation, reused across the timed loop
        lambda f: dedup.sort_unique(f, size, sentinel=sentinel)
    )
    hash_fn = jax.jit(  # noqa: DRT001 — built once per bench invocation, reused across the timed loop
        lambda f: dedup.hash_dedup(f, size, sentinel=sentinel)
    )
    x = jnp.asarray(flat)

    def timed(fn):
        jax.block_until_ready(fn(x))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(x))
            best = min(best, time.perf_counter() - t0)
        return best * 1e3

    sort_ms = timed(sort_fn)
    hash_ms = timed(hash_fn)
    overflow = int(hash_fn(x)[3])
    return {
        "N": N,
        "ratio": ratio,
        "alpha": alpha,
        "size": size,
        "sort_ms": round(sort_ms, 3),
        "hash_ms": round(hash_ms, 3),
        "speedup": round(sort_ms / hash_ms, 2) if hash_ms else None,
        "true_unique_frac": round(true_unique / N, 4),
        "overflow": overflow,
    }


REFERENCE = {"N": 26 * 2048, "ratio": 0.25, "alpha": 1.05}


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--smoke", action="store_true",
                   help="tiny grid + short windows: CI compile check")
    args = p.parse_args()

    import jax

    if args.smoke:
        grid = [(4096, 0.25, 1.05)]
        reps = 2
    else:
        grid = [
            (N, ratio, alpha)
            for N in (8192, 26 * 2048)
            for ratio in (0.25, 0.5, 1.0)
            for alpha in (1.05, 1.2)
        ]
        reps = args.reps

    rows = [_bench_one(N, r, a, reps) for (N, r, a) in grid]
    ref = next(
        (
            row for row in rows
            if (row["N"], row["ratio"], row["alpha"])
            == (REFERENCE["N"], REFERENCE["ratio"], REFERENCE["alpha"])
        ),
        None,
    )
    if ref is None and not args.smoke:
        ref = _bench_one(REFERENCE["N"], REFERENCE["ratio"],
                         REFERENCE["alpha"], reps)
    print(json.dumps({
        "metric": "dedup_sort_vs_hash",
        "rows": rows,
        "reference": ref,
        "device": jax.devices()[0].platform,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
