#!/usr/bin/env python
"""Benchmark the embedding hot path: XLA gather/scatter vs fused Pallas.

Answers the VERDICT round-1 question "does op-composed lookup reach the
roofline on TPU, or does the fused kernel win?" — the reference spent 5.5k
LoC of CUDA on this exact question for GPUs (fused_embedding_ops.cc).

Run ON HARDWARE (falls back to CPU with a warning — CPU numbers say nothing
about the TPU answer):

    python tools/bench_lookup.py [--dim 64] [--capacity 20] [--batch 16384]

Prints per-op bandwidth + a verdict line. Whichever path wins becomes the
TableConfig.kernel="auto" default.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench(fn, *args, iters=50, warmup=5):
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--capacity", type=int, default=20, help="log2 table slots")
    p.add_argument("--batch", type=int, default=16384, help="unique rows/step")
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    p.add_argument("--packed", action="store_true",
                   help="bench the packed small-dim layout (ops/packed.py) "
                        "against the unpacked logical layout at this dim — "
                        "the measurement TableConfig.packed='auto' is "
                        "waiting on (use --dim 16 for the DLRM shape)")
    p.add_argument("--traffic", action="store_true",
                   help="lookup+apply traffic-diet microbench on zipf "
                        "batches: the diet path (forward-residual reuse + "
                        "fused metadata, no apply-side re-stamps) vs the "
                        "legacy apply (re-gather + version/dirty re-stamp), "
                        "with per-arm stablehlo op counts and modeled bytes")
    p.add_argument("--zipf", type=float, default=1.05,
                   help="--traffic: zipf exponent of the id stream")
    p.add_argument("--smoke", action="store_true",
                   help="--traffic/--fused-step: tiny shapes/iters so CI "
                        "just proves both arms compile and the gates hold")
    p.add_argument("--fused-step", action="store_true",
                   help="single-pass fused sparse step (probe+gather+"
                        "combine fwd, segment-sum+apply bwd; ops/"
                        "fused_lookup.fused_sparse_*) vs the split-phase "
                        "XLA path: step time, interpret-mode parity, and "
                        "the modeled HBM bytes roofline.py --assert-fused "
                        "gates on")
    p.add_argument("--out", default=None,
                   help="--fused-step: merge the record into this JSON "
                        "file (BENCH_r07.json for the committed run)")
    args = p.parse_args(argv)
    if args.fused_step:
        return main_fused_step(args)
    if args.traffic:
        return main_traffic(args)
    if args.packed:
        return main_packed(args)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeprec_tpu.ops.fused_lookup import apply_rows_sr, gather_rows

    backend = jax.default_backend()
    if backend != "tpu":
        print(f"WARNING: running on {backend}; TPU is the question", file=sys.stderr)
    from deeprec_tpu.ops.fused_lookup import _dma_ok, _dma_pair_ok

    pair = _dma_pair_ok((1 << args.capacity, args.dim), jnp.dtype(args.dtype))
    if not _dma_ok(args.dim, jnp.dtype(args.dtype)) and not pair:
        print(
            f"WARNING: dim={args.dim} dtype={args.dtype} is ineligible for the "
            "Pallas row-DMA kernels (f32 dim%128==0) and the bf16 pair "
            "kernels (bf16 dim%128==0) — the 'pallas' rows below fall back "
            "to XLA, so the verdict is XLA-vs-XLA",
            file=sys.stderr,
        )

    C, D, U = 1 << args.capacity, args.dim, args.batch
    dt = jnp.dtype(args.dtype)
    rng = np.random.default_rng(0)
    values = jnp.asarray(rng.normal(0, 0.05, (C, D)), dt)
    ix = jnp.asarray(rng.integers(0, C, U), jnp.int32)
    rows = jnp.asarray(rng.normal(0, 0.05, (U, D)), jnp.float32)
    seed = jnp.int32(0)

    xla_gather = jax.jit(lambda v, i: v.at[i].get(mode="clip"))  # noqa: DRT001 — built once per bench invocation, reused across the timed loop
    pallas_gather = jax.jit(lambda v, i: gather_rows(v, i, pair_kernels=pair))  # noqa: DRT001 — built once per bench invocation, reused across the timed loop
    xla_scatter = jax.jit(  # noqa: DRT001 — built once per bench invocation, reused across the timed loop
        lambda v, i, r: apply_rows_sr(v, i, r, seed, use_pallas=False)
    )
    pallas_scatter = jax.jit(  # noqa: DRT001 — built once per bench invocation, reused across the timed loop
        lambda v, i, r: apply_rows_sr(v, i, r, seed, use_pallas=True,
                                      pair_kernels=pair)
    )

    bytes_g = U * D * dt.itemsize  # rows read
    bytes_s = U * D * (dt.itemsize + 4)  # f32 rows in, dt rows out

    results = _run_cases((
        ("gather/xla", xla_gather, (values, ix), bytes_g),
        ("gather/pallas", pallas_gather, (values, ix), bytes_g),
        ("scatter/xla", xla_scatter, (values, ix, rows), bytes_s),
        ("scatter/pallas", pallas_scatter, (values, ix, rows), bytes_s),
    ))
    _verdicts(results, ("xla", "pallas"))
    if pair:
        print(
            "note: bf16 pair kernels measured — if pallas won both ops, flip "
            "AUTO_TRUSTS_BF16_PAIR in ops/fused_lookup.py (measured-winners "
            "policy) so kernel='auto' serves them."
        )


def _run_cases(cases):
    """Shared bench loop: (name, fn, args, logical_bytes) -> {name: GB/s}."""
    results = {}
    for name, fn, fargs, nbytes in cases:
        dt_s = bench(fn, *fargs)
        gbps = nbytes / dt_s / 1e9
        results[name] = gbps
        print(f"{name:20s} {dt_s * 1e6:9.1f} us   {gbps:8.1f} GB/s")
    return results


def _verdicts(results, arms, threshold=1.05):
    """Per-op winner lines for a two-arm comparison, 5% tie band."""
    a, b = arms
    for op in ("gather", "scatter"):
        ka = next(k for k in results if k.startswith(f"{op}/{a}"))
        kb = next(k for k in results if k.startswith(f"{op}/{b}"))
        va, vb = results[ka], results[kb]
        winner = b if vb > va * threshold else (a if va > vb * threshold
                                                else "tie")
        print(f"verdict[{op}]: {winner} ({a} {va:.1f} vs {b} {vb:.1f} GB/s)")


def main_traffic(args):
    """Traffic-diet microbench: the full train lookup+apply pair for one
    table on zipf-skewed ids, diet arm vs legacy-apply arm.

    Both arms share the table layout (the fused [3, C] metadata leaf is
    structural); the arms differ exactly by what the diet removed from the
    apply — the [U, D] value re-gather and the version/dirty re-stamp pair
    (`apply_gradients(reuse_rows=, stamp_meta=)`) — so the delta isolates
    the diet's win.  The op-count lines additionally show the fused-meta
    structural saving against the recorded pre-diet inventory
    (ops/traffic.py).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.data.synthetic import zipf_ids
    from deeprec_tpu.embedding.table import EmbeddingTable
    from deeprec_tpu.ops import dedup
    from deeprec_tpu.ops.traffic import (
        count_stablehlo_ops, table_step_traffic,
    )
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.optim.apply import apply_gradients, ensure_slots

    if args.smoke:
        cap_log2, N, iters = min(args.capacity, 14), 4096, 5
    else:
        cap_log2, N, iters = args.capacity, args.batch, 30
    D = args.dim
    cfg = TableConfig(name="traffic_bench", dim=D, capacity=1 << cap_log2,
                      value_dtype=args.dtype)
    t = EmbeddingTable(cfg)
    opt = Adagrad(lr=0.05)
    state0 = ensure_slots(t, t.create(), opt)
    rng = np.random.default_rng(0)
    vocab = min(1 << cap_log2, 1 << 20) // 2
    ids = jnp.asarray(zipf_ids(rng, vocab, args.zipf, (N,)), jnp.int32)
    U = dedup.resolve_size(max(N // 2, 8), N)

    def pair(diet):
        def fn(state, ids, step):
            state, res = t._lookup_unique_impl(
                state, ids, step, True, -1, U
            )
            g = jnp.ones_like(res.embeddings, jnp.float32)
            return apply_gradients(
                t, state, opt, res, g, step=step,
                reuse_rows=diet, stamp_meta=not diet,
            )
        return jax.jit(fn)  # noqa: DRT001 — built once per bench invocation, reused across the timed loop

    step = jnp.int32(1)
    arms = {"legacy_apply": pair(False), "diet": pair(True)}
    ops = {
        name: count_stablehlo_ops(fn.lower(state0, ids, step).as_text())
        for name, fn in arms.items()
    }
    # Warm the table once so every timed window hits resolved slots, then
    # INTERLEAVE the arms' timed windows (3 rounds, best window per arm) —
    # this box's single-core drift otherwise biases whichever arm runs
    # last, swamping the few-percent delta under measurement.
    st = arms["diet"](state0, ids, step)
    for fn in arms.values():  # compile both before any timing
        bench(fn, st, ids, step, iters=1, warmup=2)
    results = {name: [] for name in arms}
    for _ in range(1 if args.smoke else 3):
        for name, fn in arms.items():
            results[name].append(
                bench(fn, st, ids, step, iters=iters, warmup=1)
            )
    results = {name: min(ts) for name, ts in results.items()}
    for name in arms:
        print(f"{name:16s} {results[name] * 1e3:9.3f} ms/step (best)   "
              f"ops: {ops[name]['gather']} gathers, "
              f"{ops[name]['scatter']} scatters")
    saved_s = ops["legacy_apply"]["scatter"] - ops["diet"]["scatter"]
    speed = results["legacy_apply"] / results["diet"]
    model_b = table_step_traffic(
        unique=U, dim=D, value_bytes=jnp.dtype(args.dtype).itemsize,
        slot_widths=(D,), diet=True,
    )
    model_a = table_step_traffic(
        unique=U, dim=D, value_bytes=jnp.dtype(args.dtype).itemsize,
        slot_widths=(D,), diet=False,
    )
    print(
        f"verdict[traffic]: diet {speed:.2f}x vs legacy apply "
        f"(-{saved_s} scatter ops, -1 [U,D] gather; modeled "
        f"{model_a['hbm_bytes'] / 1e3:.1f} -> "
        f"{model_b['hbm_bytes'] / 1e3:.1f} KB/step/table, "
        f"{1 - model_b['hbm_bytes'] / model_a['hbm_bytes']:.1%} off; "
        f"fused metadata's 5->1 scatter collapse is structural and in "
        f"BOTH arms — see docs/perf.md for the full before/after)"
    )
    if saved_s <= 0:
        print("ERROR: diet removed no scatters — the apply-side "
              "re-stamps are back in the hot path", file=sys.stderr)
        sys.exit(1)
    if not args.smoke and speed < 1.0:
        print("WARNING: diet arm measured slower — investigate before "
              "trusting the removed ops on this backend", file=sys.stderr)


def main_fused_step(args):
    """Fused single-pass sparse step vs the split-phase XLA path.

    Both arms run the SAME contract (fused_sparse_forward/backward): the
    unfused arm takes the XLA fallback (hash_dedup -> gather -> combine;
    expand -> segment-add -> gather/update/scatter), the fused arm the
    Pallas kernel — interpret=True off-TPU, so off-TPU step times say
    nothing about the TPU answer and the verdict here is (a) parity and
    (b) the modeled HBM-byte ratio `roofline.py --assert-fused` gates on.
    Both arms are jitted (the parity contract: matching XLA FMA
    contraction — see docs/kernels.md) and timed interleaved best-of.
    """
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeprec_tpu.data.synthetic import zipf_ids
    from deeprec_tpu.ops import dedup
    from deeprec_tpu.ops import fused_lookup as fl
    from deeprec_tpu.ops.traffic import fused_sparse_step_traffic
    from deeprec_tpu.optim import Adagrad

    backend = jax.default_backend()
    if backend != "tpu":
        print(f"WARNING: running on {backend}; fused arm runs "
              "interpret=True — times say nothing about TPU",
              file=sys.stderr)
    if args.smoke:
        B, L, cap_log2, budget, iters, rounds = 32, 4, 9, 31, 2, 1
    else:
        B, L, cap_log2, budget, iters, rounds = 256, 4, 12, 127, 8, 3
    D, C, N = args.dim, 1 << cap_log2, B * L
    U = dedup.resolve_size(budget, N)
    dt = jnp.dtype(args.dtype)
    interp = backend != "tpu"
    combiner = "mean"
    opt = Adagrad(lr=0.05)
    slot_widths = tuple(
        shape[0] for name, (shape, _) in opt.slot_specs(D).items()
    )

    rng = np.random.default_rng(0)
    values = jnp.asarray(rng.normal(0, 0.05, (C, D)), dt)
    slots = {
        name: jnp.full((C, D), init, jnp.float32)
        for name, (shape, init) in opt.slot_specs(D).items()
    }
    # vocab < budget so overflow == 0: with overflow, WHICH distinct ids
    # make the budget is path-dependent (both answers valid), and the
    # bitwise parity probe below would compare two different samples. The
    # heavy duplication this produces is also the regime the dedup engine
    # exists for (zipf-skewed bag features).
    ids = np.asarray(zipf_ids(rng, max(budget // 2, 4), args.zipf, (B, L)))
    ids[rng.random((B, L)) < 0.1] = -1  # pads, like real bag features
    ids = jnp.asarray(ids, jnp.int32)

    def make_fwd(fused):
        def fn(v, i):
            return fl.fused_sparse_forward(
                v, i, combiner=combiner, unique_size=U,
                interpret=fused and interp, use_pallas=fused,
            )
        return jax.jit(fn)  # noqa: DRT001 — built once per bench invocation, reused across the timed loop

    def make_step(fused):
        def fn(v, s, i):
            res = fl.fused_sparse_forward(
                v, i, combiner=combiner, unique_size=U,
                interpret=fused and interp, use_pallas=fused,
            )
            g = res.out + 1.0  # any grad; keeps fwd in the timed graph
            return fl.fused_sparse_backward(
                v, s, g, i, res, opt, combiner=combiner, step=1, seed=7,
                interpret=fused and interp, use_pallas=fused,
            )
        return jax.jit(fn)  # noqa: DRT001 — built once per bench invocation, reused across the timed loop

    # --- parity probe (the oracle contract, both sides jitted) ---
    out_u = make_fwd(False)(values, ids)
    out_f = make_fwd(True)(values, ids)
    fwd_ok = bool(jnp.array_equal(out_u.out, out_f.out))
    (v_u, s_u), (v_f, s_f) = (
        make_step(False)(values, slots, ids),
        make_step(True)(values, slots, ids),
    )
    bwd_ok = bool(jnp.array_equal(v_u, v_f)) and all(
        bool(jnp.array_equal(s_u[k], s_f[k])) for k in s_u
    )
    vb16 = values.astype(jnp.bfloat16)
    vb_u, _ = make_step(False)(vb16, slots, ids)
    vb_f, _ = make_step(True)(vb16, slots, ids)
    sr_ok = bool(jnp.array_equal(vb_u, vb_f))

    # --- timing: interleaved best-of, like --traffic ---
    arms = {"unfused": make_step(False), "fused": make_step(True)}
    for fn in arms.values():
        bench(fn, values, slots, ids, iters=1, warmup=2)
    times = {name: [] for name in arms}
    for _ in range(rounds):
        for name, fn in arms.items():
            times[name].append(
                bench(fn, values, slots, ids, iters=iters, warmup=1)
            )
    times = {name: min(ts) for name, ts in times.items()}

    model = {
        arm: fused_sparse_step_traffic(
            positions=N, batch=B, unique=U, dim=D, value_bytes=dt.itemsize,
            slot_widths=slot_widths, fused=(arm == "fused"),
        )["hbm_bytes"]
        for arm in ("unfused", "fused")
    }
    ratio = model["fused"] / model["unfused"]
    for name in arms:
        print(f"{name:10s} {times[name] * 1e3:9.3f} ms/step (best)   "
              f"modeled {model[name] / 1e3:10.1f} KB/step/table")
    print(
        f"verdict[fused-step]: modeled HBM {ratio:.3f}x unfused "
        f"(gate <= 0.6); parity fwd={fwd_ok} bwd={bwd_ok} bf16_sr={sr_ok} "
        f"on {backend}" + (" (interpret)" if interp else "")
    )
    record = {
        "fused_step": {
            "shapes": {
                "batch": B, "bag": L, "positions": N, "unique": U,
                "dim": D, "capacity": C, "dtype": str(dt),
                "optimizer": "adagrad", "combiner": combiner,
                "slot_widths": list(slot_widths),
            },
            "arms": {n: {"ms": times[n] * 1e3} for n in arms},
            "modeled": {
                "unfused_hbm_bytes": model["unfused"],
                "fused_hbm_bytes": model["fused"],
                "ratio": ratio,
            },
            "parity": {
                "forward_bitwise": fwd_ok,
                "backward_bitwise": bwd_ok,
                "bf16_sr_bitwise": sr_ok,
            },
            "backend": backend + ("/interpret" if interp else ""),
        }
    }
    if args.out:
        merged = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                merged = json.load(f)
        merged.update(record)
        with open(args.out, "w") as f:
            json.dump(merged, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"recorded -> {args.out}")
    if not (fwd_ok and bwd_ok and sr_ok):
        print("ERROR: fused step lost oracle parity vs the split-phase "
              "path", file=sys.stderr)
        sys.exit(1)


def main_packed(args):
    """Packed-vs-unpacked layout at dim < 128: same logical op, two
    storage layouts, each arm running the kernels production's
    kernel='auto' would serve it (the packed array is DMA-eligible at
    128 lanes; the unpacked small-dim arm self-gates to XLA). On TPU the
    packed array dodges the 128-lane minor-dim padding (P× less HBM read
    per gather); on CPU it measured -36% (BENCH_r04 vs r03) — this
    prints the per-backend verdict the TableConfig.packed='auto' gate
    encodes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeprec_tpu.ops.fused_lookup import (
        AUTO_TRUSTS_BF16_PAIR, AUTO_TRUSTS_F32_ROW,
    )
    from deeprec_tpu.ops.packed import (
        gather_rows_any, pack_array, pack_factor, scatter_rows_any,
    )

    backend = jax.default_backend()
    if backend != "tpu":
        print(f"WARNING: running on {backend}; TPU is the question",
              file=sys.stderr)
    C, D = 1 << args.capacity, args.dim
    U = min(args.batch, C)  # scatter contract needs unique slots
    if U < args.batch:
        print(f"note: batch clamped to capacity ({U}) for unique-slot "
              "scatter", file=sys.stderr)
    P = pack_factor(D, C)
    if P == 1:
        print(f"dim={D} capacity=2^{args.capacity} does not pack "
              "(need dim<128, dim|128, capacity%(128//dim)==0)",
              file=sys.stderr)
        return
    dt = jnp.dtype(args.dtype)
    rng = np.random.default_rng(0)
    logical = jnp.asarray(rng.normal(0, 0.05, (C, D)), dt)
    packed = pack_array(logical, P)
    ix = jnp.asarray(rng.integers(0, C, U), jnp.int32)
    rows = jnp.asarray(rng.normal(0, 0.05, (U, D)), jnp.float32)
    uix = jnp.asarray(rng.permutation(C)[:U].astype(np.int32))

    # Match production's kernel='auto' flags; the layout-polymorphic ops
    # dispatch per arm from the array shape, and ineligible shapes
    # self-gate back to XLA exactly as they do in the table hot path.
    kw = dict(use_pallas=AUTO_TRUSTS_F32_ROW,
              pair_kernels=AUTO_TRUSTS_BF16_PAIR)
    g = jax.jit(lambda v, i: gather_rows_any(v, i, C, **kw))  # noqa: DRT001 — built once per bench invocation, reused across the timed loop
    s = jax.jit(lambda v, i, r: scatter_rows_any(v, i, r, C, **kw))  # noqa: DRT001 — built once per bench invocation, reused across the timed loop

    bytes_g = U * D * dt.itemsize
    bytes_s = U * D * (dt.itemsize + 4)
    results = _run_cases((
        ("gather/unpacked", g, (logical, ix), bytes_g),
        (f"gather/packed_x{P}", g, (packed, ix), bytes_g),
        ("scatter/unpacked", s, (logical, uix, rows), bytes_s),
        (f"scatter/packed_x{P}", s, (packed, uix, rows), bytes_s),
    ))
    _verdicts(results, ("unpacked", "packed"))
    print("note: GB/s counts LOGICAL bytes, so the packed arm's TPU "
          "advantage (no lane padding) shows up as higher throughput; on "
          "TPU a packed win validates TableConfig.packed='auto' — record "
          "the numbers in docs/perf.md.")


if __name__ == "__main__":
    main()
