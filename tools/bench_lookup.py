#!/usr/bin/env python
"""Benchmark the embedding hot path: XLA gather/scatter vs fused Pallas.

Answers the VERDICT round-1 question "does op-composed lookup reach the
roofline on TPU, or does the fused kernel win?" — the reference spent 5.5k
LoC of CUDA on this exact question for GPUs (fused_embedding_ops.cc).

Run ON HARDWARE (falls back to CPU with a warning — CPU numbers say nothing
about the TPU answer):

    python tools/bench_lookup.py [--dim 64] [--capacity 20] [--batch 16384]

Prints per-op bandwidth + a verdict line. Whichever path wins becomes the
TableConfig.kernel="auto" default.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench(fn, *args, iters=50, warmup=5):
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--capacity", type=int, default=20, help="log2 table slots")
    p.add_argument("--batch", type=int, default=16384, help="unique rows/step")
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    p.add_argument("--packed", action="store_true",
                   help="bench the packed small-dim layout (ops/packed.py) "
                        "against the unpacked logical layout at this dim — "
                        "the measurement TableConfig.packed='auto' is "
                        "waiting on (use --dim 16 for the DLRM shape)")
    args = p.parse_args(argv)
    if args.packed:
        return main_packed(args)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeprec_tpu.ops.fused_lookup import apply_rows_sr, gather_rows

    backend = jax.default_backend()
    if backend != "tpu":
        print(f"WARNING: running on {backend}; TPU is the question", file=sys.stderr)
    from deeprec_tpu.ops.fused_lookup import _dma_ok, _dma_pair_ok

    pair = _dma_pair_ok((1 << args.capacity, args.dim), jnp.dtype(args.dtype))
    if not _dma_ok(args.dim, jnp.dtype(args.dtype)) and not pair:
        print(
            f"WARNING: dim={args.dim} dtype={args.dtype} is ineligible for the "
            "Pallas row-DMA kernels (f32 dim%128==0) and the bf16 pair "
            "kernels (bf16 dim%128==0) — the 'pallas' rows below fall back "
            "to XLA, so the verdict is XLA-vs-XLA",
            file=sys.stderr,
        )

    C, D, U = 1 << args.capacity, args.dim, args.batch
    dt = jnp.dtype(args.dtype)
    rng = np.random.default_rng(0)
    values = jnp.asarray(rng.normal(0, 0.05, (C, D)), dt)
    ix = jnp.asarray(rng.integers(0, C, U), jnp.int32)
    rows = jnp.asarray(rng.normal(0, 0.05, (U, D)), jnp.float32)
    seed = jnp.int32(0)

    xla_gather = jax.jit(lambda v, i: v.at[i].get(mode="clip"))
    pallas_gather = jax.jit(lambda v, i: gather_rows(v, i, pair_kernels=pair))
    xla_scatter = jax.jit(
        lambda v, i, r: apply_rows_sr(v, i, r, seed, use_pallas=False)
    )
    pallas_scatter = jax.jit(
        lambda v, i, r: apply_rows_sr(v, i, r, seed, use_pallas=True,
                                      pair_kernels=pair)
    )

    bytes_g = U * D * dt.itemsize  # rows read
    bytes_s = U * D * (dt.itemsize + 4)  # f32 rows in, dt rows out

    results = _run_cases((
        ("gather/xla", xla_gather, (values, ix), bytes_g),
        ("gather/pallas", pallas_gather, (values, ix), bytes_g),
        ("scatter/xla", xla_scatter, (values, ix, rows), bytes_s),
        ("scatter/pallas", pallas_scatter, (values, ix, rows), bytes_s),
    ))
    _verdicts(results, ("xla", "pallas"))
    if pair:
        print(
            "note: bf16 pair kernels measured — if pallas won both ops, flip "
            "AUTO_TRUSTS_BF16_PAIR in ops/fused_lookup.py (measured-winners "
            "policy) so kernel='auto' serves them."
        )


def _run_cases(cases):
    """Shared bench loop: (name, fn, args, logical_bytes) -> {name: GB/s}."""
    results = {}
    for name, fn, fargs, nbytes in cases:
        dt_s = bench(fn, *fargs)
        gbps = nbytes / dt_s / 1e9
        results[name] = gbps
        print(f"{name:20s} {dt_s * 1e6:9.1f} us   {gbps:8.1f} GB/s")
    return results


def _verdicts(results, arms, threshold=1.05):
    """Per-op winner lines for a two-arm comparison, 5% tie band."""
    a, b = arms
    for op in ("gather", "scatter"):
        ka = next(k for k in results if k.startswith(f"{op}/{a}"))
        kb = next(k for k in results if k.startswith(f"{op}/{b}"))
        va, vb = results[ka], results[kb]
        winner = b if vb > va * threshold else (a if va > vb * threshold
                                                else "tie")
        print(f"verdict[{op}]: {winner} ({a} {va:.1f} vs {b} {vb:.1f} GB/s)")


def main_packed(args):
    """Packed-vs-unpacked layout at dim < 128: same logical op, two
    storage layouts, each arm running the kernels production's
    kernel='auto' would serve it (the packed array is DMA-eligible at
    128 lanes; the unpacked small-dim arm self-gates to XLA). On TPU the
    packed array dodges the 128-lane minor-dim padding (P× less HBM read
    per gather); on CPU it measured -36% (BENCH_r04 vs r03) — this
    prints the per-backend verdict the TableConfig.packed='auto' gate
    encodes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeprec_tpu.ops.fused_lookup import (
        AUTO_TRUSTS_BF16_PAIR, AUTO_TRUSTS_F32_ROW,
    )
    from deeprec_tpu.ops.packed import (
        gather_rows_any, pack_array, pack_factor, scatter_rows_any,
    )

    backend = jax.default_backend()
    if backend != "tpu":
        print(f"WARNING: running on {backend}; TPU is the question",
              file=sys.stderr)
    C, D = 1 << args.capacity, args.dim
    U = min(args.batch, C)  # scatter contract needs unique slots
    if U < args.batch:
        print(f"note: batch clamped to capacity ({U}) for unique-slot "
              "scatter", file=sys.stderr)
    P = pack_factor(D, C)
    if P == 1:
        print(f"dim={D} capacity=2^{args.capacity} does not pack "
              "(need dim<128, dim|128, capacity%(128//dim)==0)",
              file=sys.stderr)
        return
    dt = jnp.dtype(args.dtype)
    rng = np.random.default_rng(0)
    logical = jnp.asarray(rng.normal(0, 0.05, (C, D)), dt)
    packed = pack_array(logical, P)
    ix = jnp.asarray(rng.integers(0, C, U), jnp.int32)
    rows = jnp.asarray(rng.normal(0, 0.05, (U, D)), jnp.float32)
    uix = jnp.asarray(rng.permutation(C)[:U].astype(np.int32))

    # Match production's kernel='auto' flags; the layout-polymorphic ops
    # dispatch per arm from the array shape, and ineligible shapes
    # self-gate back to XLA exactly as they do in the table hot path.
    kw = dict(use_pallas=AUTO_TRUSTS_F32_ROW,
              pair_kernels=AUTO_TRUSTS_BF16_PAIR)
    g = jax.jit(lambda v, i: gather_rows_any(v, i, C, **kw))
    s = jax.jit(lambda v, i, r: scatter_rows_any(v, i, r, C, **kw))

    bytes_g = U * D * dt.itemsize
    bytes_s = U * D * (dt.itemsize + 4)
    results = _run_cases((
        ("gather/unpacked", g, (logical, ix), bytes_g),
        (f"gather/packed_x{P}", g, (packed, ix), bytes_g),
        ("scatter/unpacked", s, (logical, uix, rows), bytes_s),
        (f"scatter/packed_x{P}", s, (packed, uix, rows), bytes_s),
    ))
    _verdicts(results, ("unpacked", "packed"))
    print("note: GB/s counts LOGICAL bytes, so the packed arm's TPU "
          "advantage (no lane padding) shows up as higher throughput; on "
          "TPU a packed win validates TableConfig.packed='auto' — record "
          "the numbers in docs/perf.md.")


if __name__ == "__main__":
    main()
