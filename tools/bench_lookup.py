#!/usr/bin/env python
"""Benchmark the embedding hot path: XLA gather/scatter vs fused Pallas.

Answers the VERDICT round-1 question "does op-composed lookup reach the
roofline on TPU, or does the fused kernel win?" — the reference spent 5.5k
LoC of CUDA on this exact question for GPUs (fused_embedding_ops.cc).

Run ON HARDWARE (falls back to CPU with a warning — CPU numbers say nothing
about the TPU answer):

    python tools/bench_lookup.py [--dim 64] [--capacity 20] [--batch 16384]

Prints per-op bandwidth + a verdict line. Whichever path wins becomes the
TableConfig.kernel="auto" default.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench(fn, *args, iters=50, warmup=5):
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--capacity", type=int, default=20, help="log2 table slots")
    p.add_argument("--batch", type=int, default=16384, help="unique rows/step")
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeprec_tpu.ops.fused_lookup import apply_rows_sr, gather_rows

    backend = jax.default_backend()
    if backend != "tpu":
        print(f"WARNING: running on {backend}; TPU is the question", file=sys.stderr)
    from deeprec_tpu.ops.fused_lookup import _dma_ok, _dma_pair_ok

    pair = _dma_pair_ok((1 << args.capacity, args.dim), jnp.dtype(args.dtype))
    if not _dma_ok(args.dim, jnp.dtype(args.dtype)) and not pair:
        print(
            f"WARNING: dim={args.dim} dtype={args.dtype} is ineligible for the "
            "Pallas row-DMA kernels (f32 dim%128==0) and the bf16 pair "
            "kernels (bf16 dim%128==0) — the 'pallas' rows below fall back "
            "to XLA, so the verdict is XLA-vs-XLA",
            file=sys.stderr,
        )

    C, D, U = 1 << args.capacity, args.dim, args.batch
    dt = jnp.dtype(args.dtype)
    rng = np.random.default_rng(0)
    values = jnp.asarray(rng.normal(0, 0.05, (C, D)), dt)
    ix = jnp.asarray(rng.integers(0, C, U), jnp.int32)
    rows = jnp.asarray(rng.normal(0, 0.05, (U, D)), jnp.float32)
    seed = jnp.int32(0)

    xla_gather = jax.jit(lambda v, i: v.at[i].get(mode="clip"))
    pallas_gather = jax.jit(lambda v, i: gather_rows(v, i, pair_kernels=pair))
    xla_scatter = jax.jit(
        lambda v, i, r: apply_rows_sr(v, i, r, seed, use_pallas=False)
    )
    pallas_scatter = jax.jit(
        lambda v, i, r: apply_rows_sr(v, i, r, seed, use_pallas=True,
                                      pair_kernels=pair)
    )

    bytes_g = U * D * dt.itemsize  # rows read
    bytes_s = U * D * (dt.itemsize + 4)  # f32 rows in, dt rows out

    results = {}
    for name, fn, fargs, nbytes in (
        ("gather/xla", xla_gather, (values, ix), bytes_g),
        ("gather/pallas", pallas_gather, (values, ix), bytes_g),
        ("scatter/xla", xla_scatter, (values, ix, rows), bytes_s),
        ("scatter/pallas", pallas_scatter, (values, ix, rows), bytes_s),
    ):
        dt_s = bench(fn, *fargs)
        gbps = nbytes / dt_s / 1e9
        results[name] = gbps
        print(f"{name:16s} {dt_s * 1e6:9.1f} us   {gbps:8.1f} GB/s")

    for op in ("gather", "scatter"):
        x, pl_ = results[f"{op}/xla"], results[f"{op}/pallas"]
        winner = "pallas" if pl_ > x * 1.05 else ("xla" if x > pl_ * 1.05 else "tie")
        print(f"verdict[{op}]: {winner} (xla {x:.1f} vs pallas {pl_:.1f} GB/s)")
    if pair:
        print(
            "note: bf16 pair kernels measured — if pallas won both ops, flip "
            "AUTO_TRUSTS_BF16_PAIR in ops/fused_lookup.py (measured-winners "
            "policy) so kernel='auto' serves them."
        )


if __name__ == "__main__":
    main()
