#!/bin/bash
# Probe the axon TPU tunnel once; append result to /tmp/tpu_probe.log
TS=$(date +%H:%M:%S)
OUT=$(timeout 90 python -c "import jax; d=jax.devices(); print('UP', d)" 2>&1 | tail -2)
if echo "$OUT" | grep -q "^UP"; then
  echo "$TS UP $OUT" >> /tmp/tpu_probe.log
else
  echo "$TS DOWN ${OUT:0:160}" >> /tmp/tpu_probe.log
fi
