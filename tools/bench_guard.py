"""Model-quality firewall bench: the guard/ stack measured end to end
under injected semantic poison.

Topology (one process, the CI shape of the firewall story):

    SyntheticCriteo ── PoisonInjector (NaN / extreme / label-flip /
                       stream-replayed repeats) + exploding-LR window
                              │
    TrainLoop(guard=GuardPolicy, Trainer(sentinel=SentinelConfig))
          │  sentinel trip -> rollback to verified chain + dead-letter
          ▼
    checksummed checkpoint chain (poisoned saves quarantined)
          │
    ServeLoop(quality_gate=QualityGate)  <── closed-loop scorer
          │  pre-swap canary rejects what slips through
          ▼
    GUARD_BENCH.json  (gated by roofline.py --assert-guard)

The headline gate: under the full poison matrix the SERVED model's AUC
on a held-out labeled eval set never crosses the recorded floor, ZERO
requests fail, every injected poison batch is detected within one
dispatch of its delivery, and rollback+resume completes within the
recorded wall time.

Run:  python tools/bench_guard.py [--out GUARD_BENCH.json]
      --smoke : shorter walk, same full poison matrix + asserts (CI:
                cibuild/run_tests.sh).
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

NUM_CAT, NUM_DENSE, EMB_DIM, CAPACITY = 2, 2, 8, 1 << 12
BATCH = 256


def build_model():
    from deeprec_tpu.models import WDL

    return WDL(emb_dim=EMB_DIM, capacity=CAPACITY, hidden=(32,),
               num_cat=NUM_CAT, num_dense=NUM_DENSE)


def build_trainer(sentinel=True):
    import optax

    from deeprec_tpu.guard import SentinelConfig
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    sen = SentinelConfig(
        spike_ratio=1.5, ema_decay=0.9, grad_norm_max=5e3,
        row_norm_max=50.0, row_evict_quantile=0.9,
    ) if sentinel else None
    return Trainer(build_model(), Adagrad(lr=0.1),
                   optax.adam(5e-3), sentinel=sen)


def batch_source(seed, n, sharp=4.0):
    """Synthetic-Criteo batches with SHARPENED labels: the same hidden
    id/dense structure, logits scaled by `sharp` before the label draw —
    the model reaches a real AUC (~0.86) and a clean loss floor, so a
    flipped-label batch produces an unmistakable (~2.7×) loss spike
    against the clean-step EMA (the stock generator's label noise keeps
    loss near ln 2, where a flip barely registers — detectability is
    what this bench measures, so the signal must exist)."""
    from deeprec_tpu.data import SyntheticCriteo

    gen = SyntheticCriteo(batch_size=BATCH, num_cat=NUM_CAT,
                          num_dense=NUM_DENSE, vocab=500, seed=seed)
    rng = np.random.default_rng(seed ^ 0xA5)
    out = []
    for _ in range(n):
        b = gen.batch()
        logit = np.zeros(BATCH, np.float32)
        for c in range(NUM_CAT):
            logit += gen.id_weight[c, b[f"C{c+1}"] - c * gen.vocab] * 0.3
        dense = np.concatenate(
            [b[f"I{i+1}"] for i in range(NUM_DENSE)], axis=1)
        logit += (np.log1p(dense) @ gen.dense_weight) * 0.3
        logit = (logit - logit.mean()) * sharp
        b["label"] = (
            rng.random(BATCH) < 1.0 / (1.0 + np.exp(-logit))
        ).astype(np.float32)
        out.append(b)
    return out


class Scorer(threading.Thread):
    """Closed-loop load: score the held-out eval set against the served
    model continuously; a request error anywhere fails the bench."""

    def __init__(self, serve, eval_feats, eval_labels):
        super().__init__(daemon=True, name="guard-scorer")
        self.serve = serve
        self.feats = eval_feats
        self.labels = eval_labels
        self.requests = 0
        self.failed = 0
        self.errors = []
        self.aucs = []  # (t, auc, model_version)
        self._halt = threading.Event()

    def round(self):
        from deeprec_tpu.guard.canary import np_auc

        probs = []
        ver = None
        n = len(self.labels)
        for off in range(0, n, BATCH):
            req = {k: v[off:off + BATCH] for k, v in self.feats.items()}
            self.requests += 1
            try:
                out, ver = self.serve.request_versioned(req, timeout=60.0)
            except Exception as e:  # ANY failure fails the gate
                self.failed += 1
                self.errors.append(repr(e))
                return None
            probs.append(np.asarray(out))  # noqa: DRT002 — bench scorer thread: replies are host results already
        auc = np_auc(np.concatenate(probs), self.labels)
        self.aucs.append((time.monotonic(), auc, ver))
        return auc

    def run(self):
        while not self._halt.is_set():
            self.round()
            self._halt.wait(0.1)

    def stop(self):
        self._halt.set()


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--out", default=os.path.join(REPO, "GUARD_BENCH.json"))
    p.add_argument("--dir", default=None, help="work dir (default: tmp)")
    p.add_argument("--auc-margin", type=float, default=0.05,
                   help="floor = baseline serving AUC - margin")
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    work = args.dir or tempfile.mkdtemp(prefix="deeprec_guard_")
    ck_dir = os.path.join(work, "ck")
    dl_dir = os.path.join(work, "deadletter")

    import jax.numpy as jnp

    from deeprec_tpu.guard import GuardPolicy, QualityGate
    from deeprec_tpu.online import faults
    from deeprec_tpu.online.loop import ServeLoop, TrainLoop
    from deeprec_tpu.training.checkpoint import CheckpointManager

    warm_steps = 30 if args.smoke else 80
    poison_len = 40 if args.smoke else 90

    trainer = build_trainer()
    ck = CheckpointManager(ck_dir, trainer)

    # ---- phase 1: clean warmup (anchor + a model worth defending)
    t0 = time.monotonic()
    warm = batch_source(seed=1, n=warm_steps)
    TrainLoop(trainer, ck, iter(warm), save_every=10, full_every=2,
              guard=GuardPolicy(dead_letter_dir=dl_dir, max_batch_trips=2),
              max_steps=warm_steps).run()
    print(f"warmup: {warm_steps} steps in {time.monotonic() - t0:.1f}s",
          flush=True)

    # held-out eval set + gate probe (labels ride outside the request)
    hold = batch_source(seed=99, n=2)
    eval_feats = {
        k: np.concatenate([b[k] for b in hold])
        for k in hold[0] if k != "label"
    }
    eval_labels = np.concatenate([b["label"] for b in hold])
    probe = {k: v[:BATCH] for k, v in eval_feats.items()}
    gate = QualityGate(probe=probe, labels=eval_labels[:BATCH],
                       auc_floor=0.5, max_shift=0.2)

    # ---- phase 2: serving + closed-loop scorer
    serve = ServeLoop(build_model(), ck_dir, poll_secs=0.2,
                      quality_gate=gate)
    scorer = Scorer(serve, eval_feats, eval_labels)
    baseline = scorer.round()
    if baseline is None:
        print("FATAL: baseline scoring failed", file=sys.stderr)
        return 1
    floor = round(max(0.5, baseline - args.auc_margin), 4)
    print(f"baseline serving AUC {baseline:.4f}, floor {floor}", flush=True)
    scorer.start()

    # ---- phase 3: poisoned stream (guarded trainer keeps training)
    stream = batch_source(seed=2, n=poison_len)
    plan = {6: "nan", 18: "extreme", 26: "label_flip"}
    repeats = {10, 14}  # stream-replays of the NaN batch -> permanent
    injector = faults.PoisonInjector(iter(stream), plan, repeat_at=repeats)
    lr_window = {"until": 0.0}
    base_lr = 0.1

    def lr_fn(step):
        # exploding-LR window, wall-clock-bounded (a config push that a
        # human reverts): armed once mid-run by the step hook below
        if time.monotonic() < lr_window["until"]:
            return base_lr * 1e5
        return base_lr

    armed = {"done": False}

    def on_step(step):
        if not armed["done"] and step >= warm_steps + 30:
            lr_window["until"] = time.monotonic() + 1.0
            armed["done"] = True

    loop = TrainLoop(
        trainer, ck, injector, save_every=8, full_every=3,
        guard=GuardPolicy(dead_letter_dir=dl_dir, max_batch_trips=2,
                          replay_window=128),
        lr_fn=lr_fn, on_step=on_step, log_every=0,
    )
    t_train0 = time.monotonic()
    loop.run()
    train_secs = time.monotonic() - t_train0

    # ---- phase 4: a poisoned delta slips past the trainer (shadow
    # trainer WITHOUT a sentinel writes it) — the serving canary must
    # reject it while requests keep succeeding.
    shadow = build_trainer(sentinel=False)
    ck_shadow = CheckpointManager(ck_dir, shadow)
    st = ck_shadow.restore()
    bad = faults.poison_batch(stream[-1], "nan")
    st, _ = shadow.train_step(
        st, {k: jnp.asarray(v) for k, v in bad.items()})
    ck_shadow.save_incremental(st)
    deadline = time.monotonic() + 30.0
    while gate.rejections == 0 and time.monotonic() < deadline:
        time.sleep(0.2)
    gate_health = serve.health()
    # let the scorer observe the post-rejection world for a moment
    time.sleep(1.0 if args.smoke else 3.0)
    scorer.stop()
    scorer.join(timeout=30)
    serve.close()

    # ---- ledger
    trips_by_fp = {}
    for bad_step, detect_step, flags, kinds, fp in loop.trip_log:
        trips_by_fp.setdefault(fp, []).append(
            {"step": bad_step, "detect_step": detect_step,
             "lag_dispatches": max(0, detect_step - bad_step),
             "kinds": kinds})
    events = []
    for idx, mode, fp in injector.injected:
        hits = trips_by_fp.get(fp, [])
        events.append({
            "delivery": idx, "mode": mode, "fingerprint": fp,
            "detected": bool(hits) or loop.dead_letter.is_quarantined(fp),
            "detection_dispatches": (
                max(h["lag_dispatches"] for h in hits) if hits else 0),
            "trips": len(hits),
        })
    lr_trips = [
        {"step": s, "kinds": k}
        for (s, _, _, k, fp) in loop.trip_log
        if fp not in {f for _, _, f in injector.injected}
    ]
    min_auc = min((a for _, a, _ in scorer.aucs), default=None)
    record = {
        "guard": {
            "smoke": bool(args.smoke),
            "steps": {"warmup": warm_steps, "poison_stream": poison_len},
            "events": events,
            "lr_window_trips": lr_trips,
            "trips_total": loop.guard_trips,
            "rollbacks": loop.rollbacks,
            "batches_skipped": loop.batches_skipped,
            "batches_quarantined": loop.dead_letter.permanent_count,
            "replay_gaps": loop.replay_gaps,
            "rollback_ms_last": loop.last_rollback_ms,
            "train_phase_secs": round(train_secs, 2),
            "auc": {"baseline": round(baseline, 4), "floor": floor,
                    "min_served": (round(min_auc, 4)
                                   if min_auc is not None else None),
                    "rounds": len(scorer.aucs)},
            "requests": scorer.requests,
            "failed_requests": scorer.failed,
            "request_errors": scorer.errors[:5],
            "quality_gate": {
                "rejections": gate.rejections,
                "last": gate.last_rejection,
                "health_status": gate_health.get("status"),
                "degraded_reason": gate_health.get("degraded_reason"),
            },
        }
    }

    # merge into --out (the bench JSON may carry other sections)
    existing = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                existing = json.load(f)
        except ValueError:
            existing = {}
    existing.update(record)
    with open(args.out, "w") as f:
        json.dump(existing, f, indent=1)
    print(json.dumps(record["guard"], indent=1))

    # ---- hard asserts (the bench IS the gate's producer; fail loudly)
    rc = 0
    if scorer.failed:
        print(f"FAIL: {scorer.failed} failed request(s): "
              f"{scorer.errors[:3]}", file=sys.stderr)
        rc = 1
    undetected = [e for e in events if not e["detected"]]
    if undetected:
        print(f"FAIL: undetected poison deliveries: {undetected}",
              file=sys.stderr)
        rc = 1
    slow = [e for e in events if e["detection_dispatches"] > 1]
    if slow:
        print(f"FAIL: detection slower than 1 dispatch: {slow}",
              file=sys.stderr)
        rc = 1
    if min_auc is not None and min_auc < floor:
        print(f"FAIL: served AUC {min_auc:.4f} crossed the floor {floor}",
              file=sys.stderr)
        rc = 1
    if loop.dead_letter.permanent_count < 1:
        print("FAIL: the replayed poison batch was never permanently "
              "quarantined", file=sys.stderr)
        rc = 1
    if gate.rejections < 1:
        print("FAIL: the quality gate never rejected the poisoned delta",
              file=sys.stderr)
        rc = 1
    if gate_health.get("status") != "degraded" or \
            gate_health.get("degraded_reason") != "quality_gate":
        print(f"FAIL: health after gate rejection was {gate_health}",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print(f"guard bench OK: {loop.guard_trips} trips, "
              f"{loop.rollbacks} rollbacks, "
              f"{loop.dead_letter.permanent_count} quarantined, "
              f"min served AUC {min_auc:.4f} ≥ {floor}, "
              f"{scorer.requests} requests, 0 failed")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
