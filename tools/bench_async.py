#!/usr/bin/env python
"""Measure the async embedding stage's overlap win: sync vs stale-by-one
sharded step time on the available mesh.

The async step issues the embedding exchange for batch t with no data
dependency on batch t-1's dense compute, so XLA can overlap the collective
with the matmuls (reference: async_embedding_stage.py). This tool measures
whether it does on the target hardware.

    python tools/bench_async.py [--devices 8] [--batch 4096] [--steps 30]
                                [--steps-per-dispatch K]

--steps-per-dispatch K > 1 measures the multi-step device loop
(`train_steps` / `train_steps_async`): K inner steps ride one compiled
dispatch, so the sync-vs-async comparison is repeated with host dispatch
overhead amortized K× (docs/perf.md).

On a CPU host-platform mesh the absolute numbers mean little; the TPU run
is the answer recorded in docs/perf notes.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=0, help="0 = all available")
    p.add_argument("--batch", type=int, default=4096)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--emb_dim", type=int, default=32)
    p.add_argument("--comm", default="a2a", choices=["a2a", "allgather"])
    p.add_argument("--steps-per-dispatch", type=int, default=1,
                   help="K inner steps per dispatch (lax.scan path)")
    p.add_argument("--pipeline-mode", default="off",
                   choices=["off", "lookahead", "chunked"],
                   help="add a third arm: the EXACT pipelined K-step scan "
                        "(ShardedTrainer pipeline_mode=...) next to sync "
                        "and the stale-by-one async stage — the "
                        "stale-vs-exact overlap comparison (needs "
                        "--steps-per-dispatch > 1 to engage)")
    args = p.parse_args(argv)
    K = args.steps_per_dispatch
    if K < 1:
        p.error("--steps-per-dispatch must be >= 1")
    if args.steps < 1:
        p.error("--steps must be >= 1")
    if K > 1:
        args.steps = max(K, args.steps - args.steps % K)

    import jax
    import jax.numpy as jnp
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import DLRM
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.parallel import (
        AsyncShardedTrainer,
        ShardedTrainer,
        make_mesh,
        shard_batch,
    )

    n = args.devices or len(jax.devices())
    mesh = make_mesh(n)
    model = DLRM(emb_dim=args.emb_dim, capacity=1 << 20,
                 bottom=(128, 64, args.emb_dim))
    gen = SyntheticCriteo(batch_size=args.batch, vocab=500_000, seed=0)
    batches = [
        shard_batch(mesh, {k: jnp.asarray(v) for k, v in gen.batch().items()})
        for _ in range(8)
    ]

    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeprec_tpu.training import stack_batches

    def windows():
        """[(stacked-or-single batch, steps it advances), ...] per timed
        pass — K-stacked dispatches when --steps-per-dispatch > 1."""
        if K <= 1:
            return [(batches[i % len(batches)], 1) for i in range(args.steps)]
        from deeprec_tpu.parallel.mesh import DATA_AXIS

        sh = NamedSharding(mesh, P(None, DATA_AXIS))
        return [
            (
                jax.device_put(
                    stack_batches(
                        [batches[(d * K + i) % len(batches)] for i in range(K)]
                    ),
                    sh,
                ),
                K,
            )
            for d in range(args.steps // K)
        ]

    def timed(step, state, tag):
        work = windows()
        for b, _ in work[: max(1, 3 // K)]:  # warmup: compile + fill
            state, mets = step(state, b)
        jax.block_until_ready(mets["loss"])
        t0 = time.perf_counter()
        for b, _ in work:
            state, mets = step(state, b)
        jax.block_until_ready(mets["loss"])
        dt = (time.perf_counter() - t0) / args.steps
        print(f"{tag:12s} {dt * 1e3:8.2f} ms/step "
              f"({args.batch / dt:,.0f} ex/s, K={K})")
        return dt

    sync = ShardedTrainer(model, Adagrad(lr=0.05), optax.adam(1e-3),
                          mesh=mesh, comm=args.comm)
    dt_sync = timed(
        sync.train_step if K <= 1 else sync.train_steps, sync.init(0), "sync"
    )

    dt_pipe = None
    if args.pipeline_mode != "off":
        # Exact in-step pipelining: same semantics as sync (bit-identical,
        # tests/test_pipeline_overlap.py), overlap without the staleness
        # the async arm pays. Only the K-scan path restructures, so K=1
        # measures plain sync twice.
        pipe = ShardedTrainer(model, Adagrad(lr=0.05), optax.adam(1e-3),
                              mesh=mesh, comm=args.comm,
                              pipeline_mode=args.pipeline_mode)
        dt_pipe = timed(
            pipe.train_step if K <= 1 else pipe.train_steps, pipe.init(0),
            f"exact-{args.pipeline_mode}",
        )

    asy = AsyncShardedTrainer(model, Adagrad(lr=0.05), optax.adam(1e-3),
                              mesh=mesh, comm=args.comm)
    ast = asy.bootstrap(asy.init(0), batches[0])
    dt_async = timed(
        asy.train_step_async if K <= 1 else asy.train_steps_async, ast, "async"
    )

    print(f"speedup: {dt_sync / dt_async:.3f}x "
          f"({'async wins' if dt_async < dt_sync else 'sync wins'}, "
          f"{n} devices, comm={args.comm}, steps_per_dispatch={K})")
    if dt_pipe is not None:
        print(f"exact overlap: {dt_sync / dt_pipe:.3f}x vs sync, "
              f"{dt_async / dt_pipe:.3f}x vs stale-by-one "
              f"(pipeline_mode={args.pipeline_mode}; >1.0 on the second "
              f"means exact pipelining matches the async win without "
              f"the staleness)")


if __name__ == "__main__":
    main()
