#!/usr/bin/env python
"""Measure the async embedding stage's overlap win: sync vs stale-by-one
sharded step time on the available mesh.

The async step issues the embedding exchange for batch t with no data
dependency on batch t-1's dense compute, so XLA can overlap the collective
with the matmuls (reference: async_embedding_stage.py). This tool measures
whether it does on the target hardware.

    python tools/bench_async.py [--devices 8] [--batch 4096] [--steps 30]

On a CPU host-platform mesh the absolute numbers mean little; the TPU run
is the answer recorded in docs/perf notes.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=0, help="0 = all available")
    p.add_argument("--batch", type=int, default=4096)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--emb_dim", type=int, default=32)
    p.add_argument("--comm", default="a2a", choices=["a2a", "allgather"])
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import DLRM
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.parallel import (
        AsyncShardedTrainer,
        ShardedTrainer,
        make_mesh,
        shard_batch,
    )

    n = args.devices or len(jax.devices())
    mesh = make_mesh(n)
    model = DLRM(emb_dim=args.emb_dim, capacity=1 << 20,
                 bottom=(128, 64, args.emb_dim))
    gen = SyntheticCriteo(batch_size=args.batch, vocab=500_000, seed=0)
    batches = [
        shard_batch(mesh, {k: jnp.asarray(v) for k, v in gen.batch().items()})
        for _ in range(8)
    ]

    def timed(step, state, tag):
        for i in range(3):
            state, mets = step(state, batches[i % len(batches)])
        jax.block_until_ready(mets["loss"])
        t0 = time.perf_counter()
        for i in range(args.steps):
            state, mets = step(state, batches[i % len(batches)])
        jax.block_until_ready(mets["loss"])
        dt = (time.perf_counter() - t0) / args.steps
        print(f"{tag:12s} {dt * 1e3:8.2f} ms/step "
              f"({args.batch / dt:,.0f} ex/s)")
        return dt

    sync = ShardedTrainer(model, Adagrad(lr=0.05), optax.adam(1e-3),
                          mesh=mesh, comm=args.comm)
    dt_sync = timed(sync.train_step, sync.init(0), "sync")

    asy = AsyncShardedTrainer(model, Adagrad(lr=0.05), optax.adam(1e-3),
                              mesh=mesh, comm=args.comm)
    ast = asy.bootstrap(asy.init(0), batches[0])
    dt_async = timed(asy.train_step_async, ast, "async")

    print(f"speedup: {dt_sync / dt_async:.3f}x "
          f"({'async wins' if dt_async < dt_sync else 'sync wins'}, "
          f"{n} devices, comm={args.comm})")


if __name__ == "__main__":
    main()
