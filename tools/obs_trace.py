"""Export deeprec obs trace JSONL file(s) to one Chrome-trace /
Perfetto-loadable JSON timeline.

The runtime (deeprec_tpu/obs/trace.py) appends self-contained Chrome
"X" events, one JSON object per line, to per-process files. This tool
merges any number of them — the trainer worker's, the serving process's,
the frontend's — into ``{"traceEvents": [...]}``, which
https://ui.perfetto.dev (or chrome://tracing) loads directly, so a whole
train → delta → serve round renders as one timeline and a sampled
request's trace id can be followed from the HTTP edge through the
frontend dispatch into the backend queue/pad/device/post stages.

    python tools/obs_trace.py RUN_DIR_OR_FILE... --out trace.json
    python tools/obs_trace.py trace.jsonl --summary     # ids + span names

``--trace-id HEX`` filters to one request's spans (plus untagged
process-timeline events when ``--keep-untagged`` is set).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, Iterable, List, Optional


def iter_event_files(paths: Iterable[str]) -> List[str]:
    """Expand directories to their *.jsonl members; keep files as-is."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.endswith(".jsonl")))
        else:
            out.append(p)
    return out


def load_events(paths: Iterable[str]) -> List[dict]:
    """Parse every well-formed event line; torn tails (a process killed
    mid-append) are skipped, not fatal — a trace of a fault run must
    load even when the fault hit the writer."""
    events: List[dict] = []
    for path in iter_event_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        ev = json.loads(ln)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(ev, dict) and "name" in ev and "ts" in ev:
                        events.append(ev)
        except OSError as e:
            print(f"obs_trace: cannot read {path}: {e}", file=sys.stderr)
    events.sort(key=lambda e: e.get("ts", 0))
    return events


def trace_ids(events: Iterable[dict]) -> Dict[str, List[str]]:
    """{trace_id_hex: sorted span names} over the event set."""
    out: Dict[str, set] = {}
    for ev in events:
        t = (ev.get("args") or {}).get("trace")
        if t:
            out.setdefault(t, set()).add(ev["name"])
    return {t: sorted(names) for t, names in out.items()}


def export(paths: Iterable[str], out_path: str,
           trace_id: Optional[str] = None,
           keep_untagged: bool = True) -> Dict:
    """Write the merged Chrome JSON; returns a small report
    (event/trace counts) the benches record."""
    events = load_events(paths)
    if trace_id:
        events = [
            ev for ev in events
            if (ev.get("args") or {}).get("trace") == trace_id
            or (keep_untagged and "trace" not in (ev.get("args") or {}))
        ]
    # Process-name metadata rows make the Perfetto track list readable.
    meta = []
    seen_pids = {}
    for ev in events:
        pid = ev.get("pid")
        svc = (ev.get("args") or {}).get("service")
        if pid is not None and pid not in seen_pids:
            seen_pids[pid] = svc or f"pid {pid}"
    for pid, name in sorted(seen_pids.items()):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": name}})
    doc = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return {
        "events": len(events),
        "processes": len(seen_pids),
        "traces": len(trace_ids(events)),
        "out": out_path,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("inputs", nargs="+",
                   help="obs JSONL file(s) or directories of them")
    p.add_argument("--out", default=None,
                   help="write the merged Chrome/Perfetto JSON here")
    p.add_argument("--trace-id", default=None,
                   help="keep only spans of this trace id (16-hex)")
    p.add_argument("--drop-untagged", action="store_true",
                   help="with --trace-id: also drop process-timeline "
                        "events that carry no trace id")
    p.add_argument("--summary", action="store_true",
                   help="print trace ids and their span names, no export")
    args = p.parse_args(argv)

    if args.summary or not args.out:
        events = load_events(args.inputs)
        ids = trace_ids(events)
        print(json.dumps({
            "events": len(events),
            "traces": {t: names for t, names in sorted(ids.items())},
        }, indent=1))
        return 0
    rep = export(args.inputs, args.out, trace_id=args.trace_id,
                 keep_untagged=not args.drop_untagged)
    print(json.dumps(rep))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
