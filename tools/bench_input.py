"""Microbenchmark: the parallel host input pipeline vs the serial reader.

Measures what data/pipeline.py moved off the training thread (PR 20):

  * parse — ex/s and MB/s of the vectorized `criteo_block_parse` vs the
    serial `criteo_line_parser` hot loop on the SAME bytes (the
    `block_parse_speedup` the --assert-input gate pins at >=2x).
  * stages — the pipeline's own per-stage accounting (read/parse/pack
    worker-seconds + consumer stall) and end-to-end pipeline ex/s at 1
    and N workers.
  * parity — bit-identity of the batch stream: N workers vs 1 worker vs
    a serial `criteo_line_parser` assembly of the same files
    (`parity_ok`; any mismatch fails the gate).
  * train thread — host time per dispatch on the training thread: a
    queue pop from the pre-filled pipeline vs parsing the batch inline
    (`train_thread_ratio`; the gate pins no regression).

Prints ONE JSON line with an "input" section (the bench.py convention).
`--smoke` shrinks the row count so CI merely proves the gates hold
(cibuild/run_tests.sh); real numbers come from a full run
(INPUT_BENCH.json).
"""
import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def write_criteo(dirname, files, rows_per_file, seed=0):
    """Realistic-shape Criteo TSV: zipf-repeated categorical values (the
    measured regime for the unique-based id hashing — matches the skew of
    SyntheticCriteo), ~10% missing fields."""
    rng = np.random.default_rng(seed)
    vocabs = [[f"{v:x}" for v in rng.integers(0, 1 << 20, size=2000)]
              for _ in range(26)]
    paths = []
    for fi in range(files):
        p = os.path.join(dirname, f"day{fi}.tsv")
        with open(p, "w") as f:
            zipf = (rng.zipf(1.3, size=(rows_per_file, 26)) - 1) % 2000
            miss = rng.random((rows_per_file, 13)) < 0.1
            labels = rng.integers(0, 2, rows_per_file)
            dense = rng.integers(0, 100, (rows_per_file, 13))
            for r in range(rows_per_file):
                cols = [str(labels[r])]
                cols += ["" if miss[r, i] else str(dense[r, i])
                         for i in range(13)]
                cols += [vocabs[c][zipf[r, c]] for c in range(26)]
                f.write("\t".join(cols) + "\n")
        paths.append(p)
    return paths


def bench_parse(paths, reps, B=512, shard_batches=16):
    """Block parse vs serial line parse on identical bytes, each at its
    real operating grain: the pipeline hands `criteo_block_parse` a
    shard (shard_batches * B records) at a time; the serial readers hand
    `criteo_line_parser` one batch (B lines) at a time."""
    from deeprec_tpu.data.readers import RecordErrors, criteo_block_parse
    from deeprec_tpu.data.stream import criteo_line_parser

    data = b"".join(open(p, "rb").read() for p in paths)
    lines = data.decode().split("\n")[:-1]
    n = len(lines)
    mb = len(data) / 1e6
    ends = np.flatnonzero(np.frombuffer(data, np.uint8) == 10) + 1
    shard = shard_batches * B
    bounds = [0] + [int(ends[min(i + shard, n) - 1])
                    for i in range(0, n, shard)]

    def cat(chunks):
        return {k: np.concatenate([c[k] for c in chunks]) for k in
                chunks[0]}

    tb = 1e30
    for _ in range(reps):
        err = RecordErrors(metrics=False)
        t0 = time.perf_counter()
        got = [criteo_block_parse(data[lo:hi], errors=err)
               for lo, hi in zip(bounds[:-1], bounds[1:])]
        tb = min(tb, time.perf_counter() - t0)
    got = cat(got)
    ts = 1e30
    for _ in range(reps):
        parse = criteo_line_parser(errors=RecordErrors(metrics=False))
        t0 = time.perf_counter()
        want = [parse(lines[i:i + B]) for i in range(0, n, B)]
        ts = min(ts, time.perf_counter() - t0)
    want = cat(want)
    parse_parity = all(
        (got[k] == want[k]).all() and got[k].dtype == want[k].dtype
        for k in want
    )
    return {
        "records": n,
        "mb": round(mb, 3),
        "block_exps": round(n / tb, 1),
        "block_mbps": round(mb / tb, 2),
        "serial_exps": round(n / ts, 1),
        "serial_mbps": round(mb / ts, 2),
        "block_parse_speedup": round(ts / tb, 3),
        "parse_parity": bool(parse_parity),
    }


def serial_stream(paths, B):
    """The serial baseline stream: per-file `criteo_line_parser` batches,
    remainder dropped per file (the reader contract)."""
    from deeprec_tpu.data.readers import RecordErrors, sanitize_batch
    from deeprec_tpu.data.stream import criteo_line_parser

    err = RecordErrors(metrics=False)
    parse = criteo_line_parser(errors=err)
    for p in paths:
        with open(p) as f:
            lines = f.read().split("\n")[:-1]
        for i in range(len(lines) // B):
            yield sanitize_batch(parse(lines[i * B:(i + 1) * B]), err)


def bench_pipeline(paths, B, workers):
    from deeprec_tpu.data.pipeline import ParallelInputPipeline

    pl = ParallelInputPipeline(paths, batch_size=B, num_workers=workers,
                               metrics=False)
    t0 = time.perf_counter()
    batches = list(pl)
    wall = time.perf_counter() - t0
    stats = pl.stats()
    pl.close()
    n = sum(b["label"].shape[0] for b in batches)
    return batches, {
        "workers": workers,
        "batches": len(batches),
        "exps": round(n / wall, 1),
        "wall_s": round(wall, 4),
        "read_s": round(stats["read_s"], 4),
        "parse_s": round(stats["parse_s"], 4),
        "pack_s": round(stats["pack_s"], 4),
        "stall_s": round(stats["stall_s"], 4),
        "mbps": round(stats["bytes"] / 1e6 / wall, 2),
    }


def bench_train_thread(paths, B, workers, reps):
    """Host time per dispatch ON THE TRAINING THREAD: a pop from the
    pre-filled pipeline buffer vs parsing the batch inline (what the
    training thread did before PR 20). The pipeline is given a window
    covering the whole (bench-sized) stream and drained only after the
    workers finish, so the pop numbers measure the pop, not the worker."""
    from deeprec_tpu.data.pipeline import ParallelInputPipeline

    pop_us = 1e30
    nb = 0
    for _ in range(reps):
        pl = ParallelInputPipeline(paths, batch_size=B,
                                   num_workers=workers,
                                   reorder_window=1 << 30, metrics=False)
        first = next(pl)  # starts the workers
        deadline = time.time() + 60
        while len(pl._buf) < pl.total_units - 1 and time.time() < deadline:
            time.sleep(0.01)
        t0 = time.perf_counter()
        rest = list(pl)
        dt = time.perf_counter() - t0
        nb = 1 + len(rest)
        pop_us = min(pop_us, dt / max(1, len(rest)) * 1e6)
        pl.close()
        del first, rest

    t0 = time.perf_counter()
    serial_n = sum(1 for _ in serial_stream(paths, B))
    serial_us = (time.perf_counter() - t0) / max(1, serial_n) * 1e6
    return {
        "batches": nb,
        "pop_us": round(pop_us, 2),
        "serial_inline_us": round(serial_us, 2),
        "train_thread_ratio": round(pop_us / serial_us, 5),
    }


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="small row count: CI proves the gates, not perf")
    p.add_argument("--rows", type=int, default=None,
                   help="rows per file (default 40000, smoke 4000)")
    p.add_argument("--files", type=int, default=3)
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--reps", type=int, default=None)
    p.add_argument("--out", type=str, default=None,
                   help="also write the JSON here (for roofline gates)")
    args = p.parse_args()
    rows = args.rows or (4000 if args.smoke else 40000)
    reps = args.reps or (2 if args.smoke else 4)

    tmp = tempfile.mkdtemp(prefix="deeprec_bench_input_")
    try:
        paths = write_criteo(tmp, args.files, rows)
        parse = bench_parse(paths, reps)

        want = list(serial_stream(paths, args.batch))
        runs = []
        stream_parity = True
        for w in sorted({1, 2, args.workers}):
            got, stats = bench_pipeline(paths, args.batch, w)
            runs.append(stats)
            ok = len(got) == len(want) and all(
                (a[k] == b[k]).all() and a[k].dtype == b[k].dtype
                for a, b in zip(got, want) for k in b
            )
            stream_parity = stream_parity and ok

        train = bench_train_thread(paths, args.batch, args.workers, reps)

        out = {
            "input": {
                "rows": rows * args.files,
                "batch": args.batch,
                "block_parse_speedup": parse["block_parse_speedup"],
                "parity_ok": bool(parse["parse_parity"] and stream_parity),
                "parse": parse,
                "pipeline": runs,
                "train_thread": train,
                "train_thread_ratio": train["train_thread_ratio"],
            }
        }
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
