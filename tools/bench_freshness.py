"""Train-to-serve freshness bench: the online-learning loop, measured
end to end and under injected faults.

Topology (one host, the CI shape of the DeepRec online story):

    appender ──> stream.txt ──> FileStreamServer (broker, TCP)
                                      │
                            TCPStreamReader (offset resume)
                                      │
            trainer SUBPROCESS (online.loop worker, supervised:
            heartbeat lease + restart budget) ── save_incremental_async
                                      │
                              checkpoint chain (checksummed)
                                      │
            ServeLoop (Predictor + ModelServer, poll thread) <── load gen

Headline metric: **freshness lag** — the time from an example landing in
the stream file to the FIRST prediction served by a model state that has
trained on it (ingest -> consume -> train -> delta save -> poll ->
verify -> replay -> warm -> swap -> serve). Batches map to steps exactly
(B lines = 1 step, offsets are exactly-once across restarts), so step s
is "reflected" once a request is answered by a snapshot whose train step
>= s.

Fault phases (each measured under sustained request load, each required
to finish with ZERO failed serving requests):

  * trainer_sigkill    — kill -9 the trainer; the supervisor restarts it
                         and it resumes from the chain + stream offsets.
  * corrupt_delta      — bit-flip a committed, not-yet-applied delta;
                         serving must quarantine it and serve through;
                         the trainer's next save self-heals (full).
  * broker_disconnect  — take the TCP broker down and revive it; the
                         reader reconnects with jittered backoff.

Run:  python tools/bench_freshness.py [--seconds 20] [--rps 25]
      [--out FRESHNESS_BENCH.json]
      --smoke : short steady window + one trainer kill, asserts recovery
                and zero failed requests (CI: cibuild/run_tests.sh).
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

# Model/schema shared by the trainer worker (online.loop main) and the
# in-process ServeLoop — must stay in lockstep with the worker defaults.
NUM_CAT, NUM_DENSE, EMB_DIM, CAPACITY = 2, 2, 4, 1 << 12


def build_model():
    from deeprec_tpu.models import WDL

    return WDL(emb_dim=EMB_DIM, capacity=CAPACITY, hidden=(16,),
               num_cat=NUM_CAT, num_dense=NUM_DENSE)


class LineGen:
    """Deterministic Criteo-shaped TSV lines (label, I*, C*) the stream
    broker serves and criteo_line_parser consumes."""

    def __init__(self, seed: int = 0):
        self.rng = np.random.default_rng(seed)

    def lines(self, n: int):
        out = []
        for _ in range(n):
            label = int(self.rng.random() < 0.4)
            dense = [f"{self.rng.lognormal(0.0, 1.0):.3f}"
                     for _ in range(NUM_DENSE)]
            cats = [f"tok{int(self.rng.integers(0, 400))}"
                    for _ in range(NUM_CAT)]
            out.append("\t".join([str(label)] + dense + cats))
        return out


class Ingestor(threading.Thread):
    """Append `batch` lines to the stream file `per_sec` times a second,
    recording (total_lines, t_monotonic) after each durable append —
    the ingest-time side of the freshness ledger."""

    def __init__(self, path: str, batch: int, per_sec: float):
        super().__init__(daemon=True, name="ingestor")
        self.path = path
        self.batch = batch
        self.period = 1.0 / per_sec
        self.gen = LineGen()
        self.marks = []  # [(total_lines, t)]
        self.total = 0
        self._stop = threading.Event()

    def run(self):
        nxt = time.monotonic()
        while not self._stop.is_set():
            data = "\n".join(self.gen.lines(self.batch)) + "\n"
            with open(self.path, "a") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            self.total += self.batch
            self.marks.append((self.total, time.monotonic()))
            nxt += self.period
            delay = nxt - time.monotonic()
            if delay > 0:
                self._stop.wait(delay)

    def stop(self):
        self._stop.set()

    def ingest_time_of_step(self, step: int, batch_size: int):
        """When the LAST line of train step `step` hit the file (None if
        not yet ingested)."""
        need = step * batch_size
        for total, t in self.marks:
            if total >= need:
                return t
        return None

    def first_step_after(self, t: float, batch_size: int):
        """The first train step whose data was FULLY ingested after `t`
        (the step recovery is measured against)."""
        for total, tm in self.marks:
            if tm > t:
                return total // batch_size + (1 if total % batch_size else 0)
        return None


class VersionSampler(threading.Thread):
    """Map published model versions to train steps + first-seen time.
    Publishes are >= poll_secs apart, so 20 ms sampling misses none."""

    def __init__(self, predictor):
        super().__init__(daemon=True, name="version-sampler")
        self.predictor = predictor
        self.seen = {}  # version -> (step, t_first_seen)
        self._stop = threading.Event()

    def run(self):
        while not self._stop.wait(0.02):
            v = self.predictor.version
            if v not in self.seen:
                self.seen[v] = (self.predictor.step, time.monotonic())

    def stop(self):
        self._stop.set()


class LoadGen(threading.Thread):
    """Sustained request load against the ModelServer: `rps` requests/s
    across `clients` paced threads; every response's (t_done, version)
    lands in the ledger, every exception in `failures`."""

    def __init__(self, serve, features, rps: float, clients: int = 2):
        super().__init__(daemon=True, name="loadgen")
        self.serve = serve
        self.features = features
        self.rps = rps
        self.clients = clients
        self.records = []  # [(t_done, version)]
        self.failures = []  # [(t, repr(err))]
        self._stop = threading.Event()

    def _client(self, idx: int):
        period = self.clients / self.rps
        nxt = time.monotonic() + idx * period / self.clients
        while not self._stop.is_set():
            delay = nxt - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                return
            nxt += period
            try:
                _, version = self.serve.request_versioned(
                    self.features, timeout=30,
                )
                self.records.append((time.monotonic(), version))
            except Exception as e:
                self.failures.append((time.monotonic(), repr(e)))

    def run(self):
        threads = [
            threading.Thread(target=self._client, args=(i,), daemon=True)
            for i in range(self.clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def stop(self):
        self._stop.set()

    def failures_between(self, t0: float, t1: float):
        return [f for f in self.failures if t0 <= f[0] <= t1]

    def requests_between(self, t0: float, t1: float):
        return [r for r in self.records if t0 <= r[0] <= t1]


def first_served_at_or_after(records, version_steps, step: int):
    """Earliest completion time of a request answered by a snapshot whose
    train step >= `step` (None if never)."""
    best = None
    for t_done, v in records:
        info = version_steps.get(v)
        if info is None:
            continue
        if info[0] >= step and (best is None or t_done < best):
            best = t_done
    return best


def wait_until(pred, timeout: float, poll: float = 0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    return None


def lag_stats(ingestor, loadgen, sampler, batch_size, t0, t1):
    """Freshness lag for every step fully ingested inside [t0, t1] that
    was eventually reflected in a served prediction."""
    lags = []
    steps = 0
    for total, t_in in ingestor.marks:
        if not (t0 <= t_in <= t1) or total % batch_size:
            continue
        s = total // batch_size
        steps += 1
        t_served = first_served_at_or_after(
            loadgen.records, sampler.seen, s)
        if t_served is not None and t_served >= t_in:
            lags.append(t_served - t_in)
    if not lags:
        return {"steps_ingested": steps, "steps_reflected": 0}
    lags.sort()
    return {
        "steps_ingested": steps,
        "steps_reflected": len(lags),
        "p50_s": round(lags[len(lags) // 2], 3),
        "p95_s": round(lags[min(len(lags) - 1, int(len(lags) * 0.95))], 3),
        "max_s": round(lags[-1], 3),
    }


def measure_recovery(t_fault, ingestor, loadgen, sampler, batch_size,
                     timeout):
    """Time from fault injection to the first prediction served from a
    model that trained on data ingested AFTER the fault."""
    s_f = wait_until(
        lambda: ingestor.first_step_after(t_fault, batch_size), 30)
    if s_f is None:
        return None
    t_served = wait_until(
        lambda: first_served_at_or_after(
            loadgen.records, sampler.seen, s_f),
        timeout,
    )
    return None if t_served is None else round(t_served - t_fault, 3)


def run(args):
    import signal

    from deeprec_tpu.data.stream import FileStreamServer, criteo_line_parser
    from deeprec_tpu.obs import trace as obs_trace
    from deeprec_tpu.online import faults
    from deeprec_tpu.online.loop import ServeLoop
    from deeprec_tpu.online.supervisor import Heartbeat, ProcessSpec, Supervisor

    tmp = tempfile.mkdtemp(prefix="freshness_")
    stream = os.path.join(tmp, "stream.txt")
    ckpt = os.path.join(tmp, "ckpt")
    open(stream, "w").close()
    # Cross-process tracing: the serving half appends to serve.jsonl in
    # THIS process, the supervised trainer inherits trainer.jsonl through
    # DEEPREC_TRACE — tools/obs_trace.py merges both into one
    # Perfetto-loadable train→delta→serve timeline at the end.
    trace_dir = os.path.join(tmp, "obs")
    os.makedirs(trace_dir, exist_ok=True)
    obs_trace.configure(os.path.join(trace_dir, "serve.jsonl"),
                        sample=1.0, service="serve")
    broker = FileStreamServer(stream, follow=True, poll_secs=0.02).start()

    B = args.batch_size
    ingest = Ingestor(stream, B, args.ingest_batches_per_sec)
    ingest.start()

    hb_path = os.path.join(tmp, "trainer.hb")
    spec = ProcessSpec(
        name="trainer",
        argv=[sys.executable, "-m", "deeprec_tpu.online.loop",
              "--ckpt", ckpt, "--source", f"tcp://127.0.0.1:{broker.port}",
              "--batch-size", str(B), "--save-every", str(args.save_every),
              # cadence fulls far apart: the corrupt-delta phase must
              # observe the ESCALATED self-heal full, not a scheduled one
              # racing past the corruption
              "--full-every", "40", "--steps", "1000000000",
              "--heartbeat", hb_path, "--log-every", "0"],
        heartbeat_path=hb_path,
        lease_secs=args.lease_secs,
        grace_secs=120,
        max_restarts=5,
        backoff_base_secs=0.2,
        env={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
             "DEEPREC_TRACE": os.path.join(trace_dir, "trainer.jsonl"),
             "DEEPREC_TRACE_SAMPLE": "1.0"},
        cwd=REPO,
        stdout=os.path.join(tmp, "trainer.log"),
    )
    sup = Supervisor([spec], poll_secs=0.2,
                     on_event=lambda m: print(f"# {m}", flush=True))
    sup.start()

    result = {"protocol": {
        "batch_size": B, "save_every": args.save_every,
        "ingest_batches_per_sec": args.ingest_batches_per_sec,
        "rps": args.rps, "poll_secs": args.poll_secs,
        "smoke": bool(args.smoke), "platform": "cpu",
    }}
    failed = []
    serve = None
    try:
        serve = ServeLoop(
            build_model(), ckpt, poll_secs=args.poll_secs,
            heartbeat=Heartbeat(os.path.join(tmp, "serve.hb")),
            http_port=0, max_batch=64,
            wait_for_checkpoint_secs=300,
        )
        parser = criteo_line_parser(NUM_DENSE, NUM_CAT)
        req = parser(LineGen(seed=7).lines(4))
        req.pop("label")
        serve.warmup(req)
        sampler = VersionSampler(serve.predictor)
        sampler.start()
        load = LoadGen(serve, req, rps=args.rps)
        load.start()

        # ------------------------------------------------ steady state
        t0 = time.monotonic()
        time.sleep(args.seconds)
        t1 = time.monotonic()
        # lag needs the tail of the window to be SERVED before scoring it
        time.sleep(min(10.0, args.seconds))
        steady = lag_stats(ingest, load, sampler, B, t0, t1)
        reqs = load.requests_between(t0, t1)
        steady["requests"] = len(reqs)
        steady["rps"] = round(len(reqs) / (t1 - t0), 1)
        steady["failed_requests"] = len(load.failures_between(t0, t1))
        result["steady"] = steady
        if steady.get("steps_reflected", 0) == 0:
            failed.append("steady: no steps reflected in predictions")
        if steady["failed_requests"]:
            failed.append("steady: failed requests")

        # HTTP-edge traced requests: real POST /v1/predict through the
        # ServeLoop's HttpServer so the exported timeline carries a
        # single trace id from the HTTP edge through dispatch into the
        # backend queue/pad/device/post stages (the acceptance shape).
        import urllib.request

        body = json.dumps(
            {"features": {k: np.asarray(v).tolist()  # noqa: DRT002 — host request payload serialization (name-collision reachability)
                          for k, v in req.items()}}).encode()
        for _ in range(5):
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{serve.http.port}/v1/predict",
                    data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST"),
                timeout=30).read()

        # Gauge-vs-probe agreement: the predictor's
        # train_to_serve_lag_seconds (trainer manifest commit → serving
        # swap, stamped at apply time) measures a SUFFIX of the probe's
        # ingest→served pipeline, so it must be nonnegative and bounded
        # by the probe's observed end-to-end lag (+ slack for the
        # serve-side sampling tail) — disagreement means the gauge (or
        # the manifest clock) regressed.
        lag_gauge = serve.predictor.last_apply_lag_seconds
        probe_ref = steady.get("p50_s") or steady.get("max_s")
        result["lag_gauge"] = {
            "train_to_serve_lag_seconds": lag_gauge,
            "probe_p50_s": steady.get("p50_s"),
            "probe_max_s": steady.get("max_s"),
            "tolerance_s": 1.0,
        }
        if lag_gauge is None:
            failed.append("lag_gauge: never stamped despite applied updates")
        elif probe_ref is not None and not (
                0.0 <= lag_gauge <= probe_ref + 1.0):
            failed.append(
                f"lag_gauge: {lag_gauge}s disagrees with probe lag "
                f"{probe_ref}s (+1.0s tolerance)")
        result["faults"] = {}

        # ------------------------------------------- 1. trainer SIGKILL
        tf = time.monotonic()
        restarts0 = sup.stats()["trainer"]["restarts"]
        assert sup.kill("trainer", signal.SIGKILL)
        rec = measure_recovery(tf, ingest, load, sampler, B,
                               timeout=args.recovery_timeout)
        te = time.monotonic()
        phase = {
            "recovery_s": rec,
            "failed_requests": len(load.failures_between(tf, te)),
            "supervisor_restarts":
                sup.stats()["trainer"]["restarts"] - restarts0,
        }
        result["faults"]["trainer_sigkill"] = phase
        if rec is None:
            failed.append("trainer_sigkill: no recovery")
        if phase["failed_requests"]:
            failed.append("trainer_sigkill: failed requests")

        if not args.smoke:
            # -------------------------------------- 2. corrupt delta
            serve.pause()
            time.sleep(2 * args.poll_secs + 0.2)  # drain in-flight poll

            def fresh_delta():
                applied = set(serve.predictor._applied)
                try:
                    names = os.listdir(ckpt)
                except OSError:
                    return None
                cands = [
                    d for d in names
                    if d.startswith("incr-") and "." not in d
                    and d not in applied
                    and os.path.exists(os.path.join(ckpt, d,
                                                    "manifest.json"))
                ]
                return max(cands, key=lambda d: int(d.split("-")[1])) \
                    if cands else None

            delta = wait_until(fresh_delta, 60)
            assert delta, "trainer produced no fresh delta to corrupt"
            tf = time.monotonic()
            q0 = serve.health()["quarantined"]
            corrupted = faults.corrupt_latest_delta(ckpt, mode="bitflip")
            serve.resume()
            try:
                serve.poll_now()  # synchronous detection: quarantine NOW
            except Exception:
                pass
            saw_q = wait_until(
                lambda: serve.health()["quarantined"] > q0, 60)
            rec = measure_recovery(tf, ingest, load, sampler, B,
                                   timeout=args.recovery_timeout)
            te = time.monotonic()
            healed = wait_until(
                lambda: any(
                    d.startswith("full-")
                    and int(d.split("-")[1]) > int(delta.split("-")[1])
                    for d in os.listdir(ckpt) if "." not in d
                ),
                30,
            )
            phase = {
                "corrupted": corrupted and os.path.basename(
                    os.path.dirname(corrupted)),
                "quarantined": bool(saw_q),
                "self_healed_full": bool(healed),
                "recovery_s": rec,
                "failed_requests": len(load.failures_between(tf, te)),
            }
            result["faults"]["corrupt_delta"] = phase
            if not saw_q:
                failed.append("corrupt_delta: no quarantine")
            if rec is None:
                failed.append("corrupt_delta: no recovery")
            if phase["failed_requests"]:
                failed.append("corrupt_delta: failed requests")

            # ---------------------------------- 3. broker disconnect
            outage = faults.BrokerOutage(broker)
            hb0 = Heartbeat.read(hb_path) or {}
            restarts_pre = sup.stats()["trainer"]["restarts"]
            tf = time.monotonic()
            outage.down()
            time.sleep(args.outage_secs)
            broker = outage.up()
            rec = measure_recovery(tf, ingest, load, sampler, B,
                                   timeout=args.recovery_timeout)
            te = time.monotonic()
            hb1 = Heartbeat.read(hb_path) or {}
            phase = {
                "outage_s": args.outage_secs,
                "recovery_s": rec,
                "failed_requests": len(load.failures_between(tf, te)),
                "stream_reconnects_delta":
                    hb1.get("stream_reconnects", 0)
                    - hb0.get("stream_reconnects", 0),
                "trainer_restarts_during":
                    sup.stats()["trainer"]["restarts"] - restarts_pre,
            }
            result["faults"]["broker_disconnect"] = phase
            if rec is None:
                failed.append("broker_disconnect: no recovery")
            if phase["failed_requests"]:
                failed.append("broker_disconnect: failed requests")

        load.stop()
        sampler.stop()
        result["serving_health"] = serve.health()
        result["supervisor"] = sup.stats()["trainer"]
        result["total_failed_requests"] = len(load.failures)
        result["trainer_heartbeat"] = Heartbeat.read(hb_path)
    finally:
        ingest.stop()
        sup.stop()
        if serve is not None:
            serve.close()
        try:
            broker.stop()
        except Exception:
            pass
        obs_trace.flush()

    # ------------------------------------------- timeline export + check
    # Merge the serving + trainer JSONL into one Perfetto-loadable file
    # and verify at least one HTTP-edge request's trace id spans the
    # whole serving path (edge → dispatch → queue/pad/device/post).
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import obs_trace as exporter

    trace_out = args.trace_out or os.path.join(tmp, "trace.json")
    rep = exporter.export([trace_dir], trace_out)
    ids = exporter.trace_ids(exporter.load_events([trace_dir]))
    need = {"http_predict", "dispatch", "stage_queue", "stage_pad",
            "stage_device", "stage_post"}
    complete = [t for t, names in ids.items() if need <= set(names)]
    result["trace"] = {
        "file": trace_out,
        "events": rep["events"],
        "processes": rep["processes"],
        "request_traces": len(ids),
        "complete_request_traces": len(complete),
    }
    if not complete:
        failed.append("trace: no single trace id spans HTTP edge -> "
                      "dispatch -> queue/pad/device/post")
    if rep["processes"] < 2:
        failed.append("trace: trainer process contributed no spans "
                      "(train->serve timeline incomplete)")
    result["ok"] = not failed
    if failed:
        result["failures"] = failed
    return result, failed, tmp


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--seconds", type=float, default=20.0,
                   help="steady-state measurement window")
    p.add_argument("--rps", type=float, default=25.0)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--save-every", type=int, default=8)
    p.add_argument("--ingest-batches-per-sec", type=float, default=4.0)
    p.add_argument("--poll-secs", type=float, default=0.25)
    p.add_argument("--lease-secs", type=float, default=30.0)
    p.add_argument("--outage-secs", type=float, default=6.0)
    p.add_argument("--recovery-timeout", type=float, default=180.0)
    p.add_argument("--out", default=None,
                   help="write the result JSON here (default: "
                        "FRESHNESS_BENCH.json for full runs, none for "
                        "--smoke)")
    p.add_argument("--trace-out", default=None,
                   help="write the merged Perfetto/Chrome trace JSON "
                        "here (default: <run tmpdir>/trace.json)")
    p.add_argument("--smoke", action="store_true",
                   help="CI: short steady window + one trainer kill; "
                        "asserts recovery and zero failed requests")
    args = p.parse_args(argv)
    if args.smoke:
        args.seconds = min(args.seconds, 8.0)
        args.rps = min(args.rps, 15.0)

    result, failed, tmp = run(args)
    print(json.dumps(result))
    out = args.out or (None if args.smoke else
                       os.path.join(REPO, "FRESHNESS_BENCH.json"))
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=1)
        print(f"# wrote {out}", file=sys.stderr)
    if failed:
        print(f"# FAILED: {failed}\n# artifacts: {tmp}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
