"""Fleet bench: sustained rps through membership churn with ZERO failed
requests — the headline the ROADMAP's multi-host serving item names.

Choreography (serving/fleet.py + online/supervisor.py):

  * backends run as SUPERVISED processes that announce themselves by
    lease (``--registry``); the frontend discovers/admits/retires them
    at runtime — no member list is ever configured.
  * **rolling restart of EVERY backend**: `request_drain(addr,
    respawn=True)` per member — the backend stamps ``draining``,
    frontends stop new assignments, in-flight finishes, the process
    exits EXIT_RESCALE and the Supervisor respawns it for free (the
    `parallel/elastic.py` choreography applied to serving); the new
    generation binds a fresh port and admits itself by lease.
  * **2→4→2 scale event**: the `FleetAutoscaler` (manual target — the
    deterministic bench arm of the same decision core the load policy
    drives) spawns two members through `Supervisor.add_spec`, then
    retires two by drain.
  * **fault arms**: a torn lease file planted mid-load (sweeps must
    skip it); full mode adds replicated frontends with a SIGKILLed edge
    (the FleetClient reconnect contract) and a slow joiner
    (DEEPREC_FAULT_SLOW_JOIN_SECS: reachable but unannounced — no
    routing until the lease lands).

Every phase runs under sustained closed-loop client load; ANY failed
request aborts the bench loudly. Results merge into the bench JSON as
the ``multi_host`` section (`--out SERVING_BENCH.json` updates the
committed record in place), gated by ``roofline.py --assert-serving``.

    python tools/bench_fleet.py [--smoke] [--out SERVING_BENCH.json]
        [--seconds 6] [--clients 4] [--frontends 2]

``--smoke`` (CI): 1 in-process frontend + 2 backends, shorter windows,
same rolling restart + 2→4→2 + torn-lease coverage.
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


LEASE_SECS = 3.0


def wait_for(pred, timeout, what, poll=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    raise TimeoutError(f"timed out after {timeout}s waiting for {what}")


class LoadGen:
    """Closed-loop clients running across ALL phases; phase stats come
    from slicing the request timeline. Any request failure is recorded
    and FAILS the bench — a fleet bench that drops requests silently
    would report flattering rps from a broken tier."""

    def __init__(self, client_fn, n_clients):
        self._lock = threading.Lock()
        self.recs = []           # (t_start, latency_s)
        self.errors = []
        self._stop = threading.Event()
        self.clients = [client_fn() for _ in range(n_clients)]

        def worker(client):
            while not self._stop.is_set():
                t0 = time.monotonic()
                try:
                    client["send"]()
                except Exception as e:  # any failure = bench failure
                    with self._lock:
                        self.errors.append(e)
                    return
                with self._lock:
                    self.recs.append((t0, time.monotonic() - t0))

        self.threads = [threading.Thread(target=worker, args=(c,),
                                         daemon=True)
                        for c in self.clients]

    def start(self):
        for t in self.threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for t in self.threads:
            t.join(timeout=60)
        if self.errors:
            raise RuntimeError(
                f"{len(self.errors)} client failure(s): "
                f"{self.errors[0]!r}") from self.errors[0]

    def check(self):
        with self._lock:
            if self.errors:
                raise RuntimeError(
                    f"client failure mid-phase: {self.errors[0]!r}"
                ) from self.errors[0]

    def phase_stats(self, t0, t1):
        with self._lock:
            lat = sorted(dt for (t, dt) in self.recs if t0 <= t < t1)
        n = len(lat)
        dur = max(1e-9, t1 - t0)

        def pct(q):
            return round(1e3 * lat[min(int(q * n), n - 1)], 2) if n else None

        return {"requests": n, "rps": round(n / dur, 1),
                "p50_ms": pct(0.50), "p99_ms": pct(0.99),
                "duration_s": round(dur, 2),
                "failed_requests": len(self.errors)}

    def reconnects(self):
        return sum(c.get("reconnects", lambda: 0)() for c in self.clients)


def run_bench(args):
    import numpy as np  # noqa: F401  (payload slicing below)

    from bench_serving import WDL_ARGS, build
    from deeprec_tpu.online.supervisor import ProcessSpec, Supervisor
    from deeprec_tpu.serving import fleet
    from deeprec_tpu.serving.frontend import backend_argv, spawn_frontends
    from deeprec_tpu.serving.http_server import HttpServer
    from deeprec_tpu.online import faults

    margs = dict(WDL_ARGS)
    mj = json.dumps(margs)
    ckpt = tempfile.mkdtemp(prefix="fleet_ckpt_")
    model, req, _save_next = build(ckpt, margs=margs)
    rows = args.rows
    payload = {k: np.asarray(v)[:rows] for k, v in req.items()}

    reg_dir = tempfile.mkdtemp(prefix="fleet_reg_")
    reg = fleet.FleetRegistry(reg_dir, lease_secs=LEASE_SECS)
    child_env = {"JAX_PLATFORMS": "cpu", "DEEPREC_OBS": os.environ.get(
        "DEEPREC_OBS", "")}

    def bargv(name):
        return backend_argv(ckpt=ckpt, model="wdl", model_json=mj,
                            registry=reg_dir, lease_secs=LEASE_SECS,
                            member_name=name, port=0)

    log_dir = tempfile.mkdtemp(prefix="fleet_logs_")

    def spec(name):
        return ProcessSpec(
            name=name, argv=bargv(name), lease_secs=None,
            env=dict(child_env),
            stdout=os.path.join(log_dir, f"{name}.log"))

    sup = Supervisor([spec("backend-0"), spec("backend-1")],
                     poll_secs=0.1, keep_alive=True,
                     on_event=lambda line: print(f"  {line}", flush=True))
    out = {"mode": "smoke" if args.smoke else "full",
           "protocol": {
               "model": "wdl", "model_args": margs,
               "rows_per_request": rows, "clients": args.clients,
               "lease_secs": LEASE_SECS,
               "frontends": 1 if args.smoke else args.frontends,
               "host_cores": len(os.sched_getaffinity(0)),
           }}
    fe = None
    http = None
    fprocs = []
    gen = None
    try:
        sup.start()
        wait_for(lambda: len(reg.members()) == 2, 120,
                 "2 backend leases")
        print(f"fleet: 2 supervised backends leased in {reg_dir}",
              flush=True)

        # ---- edge tier + clients
        if args.smoke:
            from deeprec_tpu.serving import Frontend

            fe = Frontend(None, model, registry=reg, membership_secs=0.2,
                          reprobe_secs=1.0)
            fe.warmup(payload)
            http = HttpServer(fe, port=0).start()
            edges = [f"127.0.0.1:{http.port}"]
        else:
            fprocs, edges = spawn_frontends(
                args.frontends, registry=reg_dir, model="wdl",
                model_json=mj, lease_secs=LEASE_SECS,
                env=dict(child_env))
        print(f"fleet: edge tier {edges}", flush=True)

        def client_fn():
            c = fleet.FleetClient(edges, registry=reg if not args.smoke
                                  else None, timeout=60.0, deadline=120.0)
            return {"send": lambda: c.predict(payload),
                    "reconnects": lambda: c.reconnects}

        # prime through the wire so every edge (and through round-robin,
        # every backend) compiles before the measured windows
        primer = fleet.FleetClient(edges, timeout=120.0, deadline=240.0)
        for _ in range(4 * len(edges)):
            primer.predict(payload)

        gen = LoadGen(client_fn, args.clients).start()

        def phase(seconds=None, until=None, what=""):
            t0 = time.monotonic()
            if until is None:
                time.sleep(seconds)
            else:
                wait_for(until, args.phase_timeout, what)
                if seconds:
                    time.sleep(seconds)
            gen.check()
            return t0, time.monotonic()

        # ---- phase 1: steady state
        t0, t1 = phase(seconds=args.seconds, what="steady")
        out["steady"] = gen.phase_stats(t0, t1)
        print(f"fleet: steady {out['steady']}", flush=True)

        # ---- phase 2: rolling restart of EVERY backend (EXIT_RESCALE)
        t0 = time.monotonic()
        rolled = 0
        fleet_size = len(reg.members())
        for m in list(reg.members()):
            old_addr = m.addr
            before = {x.addr for x in reg.members()}
            reg.request_drain(old_addr, respawn=True)
            # drained member unregisters; the supervisor respawns the
            # spec; the new generation binds a fresh port and leases it
            wait_for(
                lambda: old_addr not in
                {x.addr for x in reg.members()},
                args.phase_timeout, f"{old_addr} to drain out")
            wait_for(
                lambda: len(reg.members()) == fleet_size and
                {x.addr for x in reg.members()} != before,
                args.phase_timeout, "replacement lease")
            rolled += 1
            gen.check()
            print(f"fleet: rolled {old_addr} "
                  f"({rolled}/{fleet_size})", flush=True)
        # settle a moment of steady traffic on the new generation
        time.sleep(max(1.0, args.seconds / 3))
        t1 = time.monotonic()
        stats = sup.stats()
        out["rolling_restart"] = {
            **gen.phase_stats(t0, t1),
            "restarted": rolled,
            "fleet_size": fleet_size,
            "covered_all": rolled == fleet_size,
            "rescale_respawns": sum(
                s["rescales"] for s in stats.values()),
            "unplanned_restarts": sum(
                s["restarts"] for s in stats.values()),
        }
        print(f"fleet: rolling_restart {out['rolling_restart']}",
              flush=True)

        # ---- phase 3: 2->4->2 scale event through the autoscaler
        t0 = time.monotonic()
        scaler = fleet.attach_autoscaler(
            sup, reg, bargv, name_prefix="backend",
            env=dict(child_env), min_members=2, max_members=4,
            cooldown_secs=1.0, sustain=2)
        path = [len(reg.members(include_draining=False))]

        def drive_target(n):
            scaler.set_target(n)
            deadline = time.monotonic() + args.phase_timeout
            while True:
                scaler.observe(None)  # one tick (cooldown-paced inside)
                cur = len(reg.members(include_draining=False))
                if cur != path[-1]:
                    path.append(cur)
                gen.check()
                # settled: count right, target consumed, drained exited
                if cur == n and scaler.at_target() and \
                        len(reg.members()) == n:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"fleet never settled at {n} "
                        f"(at {cur}, leases {len(reg.members())})")
                time.sleep(0.2)

        drive_target(4)
        time.sleep(max(1.0, args.seconds / 3))  # serve a beat at 4
        drive_target(2)
        scaler.reap()          # release drained members' specs
        time.sleep(max(1.0, args.seconds / 3))
        t1 = time.monotonic()
        gen.check()
        # collapse the observed walk into the turning-point path
        turning = [path[0]]
        for v in path[1:]:
            if v != turning[-1]:
                turning.append(v)
        out["scale"] = {
            **gen.phase_stats(t0, t1),
            "path": turning,
            "target_max": 4,
            "actions": [
                {k: a[k] for k in ("action", "members_before", "why")}
                for a in scaler.actions],
        }
        print(f"fleet: scale {out['scale']}", flush=True)

        # ---- phase 4: fault arms
        out["faults"] = {}
        # torn lease mid-load: sweeps skip it, nothing degrades
        t0 = time.monotonic()
        planted = faults.torn_lease_write(reg, "10.9.9.9:1", pid=424242)
        time.sleep(1.0)
        gen.check()
        members_now = len(reg.members())
        t1 = time.monotonic()
        out["faults"]["torn_lease"] = {
            **gen.phase_stats(t0, t1),
            "planted": os.path.basename(planted),
            "members_visible": members_now,
            "member_count_unaffected": members_now == 2,
        }
        os.unlink(planted)

        if not args.smoke:
            # frontend SIGKILL: the FleetClient reconnect contract — an
            # edge death costs reconnects, never a failed request
            t0 = time.monotonic()
            victim = fprocs[0]
            pre_reconnects = gen.reconnects()
            faults.sigkill_fleet_member(victim)
            time.sleep(max(2.0, args.seconds / 2))
            gen.check()
            t1 = time.monotonic()
            out["faults"]["frontend_kill"] = {
                **gen.phase_stats(t0, t1),
                "reconnects": gen.reconnects() - pre_reconnects,
                "edges_remaining": len(edges) - 1,
            }
            print(f"fleet: frontend_kill "
                  f"{out['faults']['frontend_kill']}", flush=True)

            # slow joiner: reachable but unannounced — full service
            # meanwhile, admitted when the lease finally lands
            t0 = time.monotonic()
            import subprocess

            slow_env = {**os.environ, **child_env,
                        faults.SLOW_JOIN_ENV: "4.0"}
            sj = subprocess.Popen(
                bargv("backend-slow"), env=slow_env,
                stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
            base = len(reg.members())
            time.sleep(2.0)          # mid-join: must NOT be a member yet
            mid = len(reg.members())
            wait_for(lambda: len(reg.members()) > base,
                     args.phase_timeout, "slow joiner's lease")
            gen.check()
            t1 = time.monotonic()
            out["faults"]["slow_joiner"] = {
                **gen.phase_stats(t0, t1),
                "members_before_join": base,
                "members_mid_join": mid,
                "join_invisible_until_lease": mid == base,
            }
            sj.kill()
            sj.wait(timeout=30)

        # ---- wrap up
        gen.stop()
        gen = None
        failed = sum(
            sec.get("failed_requests", 0)
            for sec in [out["steady"], out["rolling_restart"],
                        out["scale"], *out["faults"].values()])
        out["zero_failed_requests"] = failed == 0
        out["total_requests"] = sum(
            sec.get("requests", 0)
            for sec in [out["steady"], out["rolling_restart"],
                        out["scale"], *out["faults"].values()])
        return out
    finally:
        if gen is not None:
            gen._stop.set()
        if http is not None:
            http.stop()
        if fe is not None:
            fe.close()
        for p in fprocs:
            p.kill()
        sup.stop()


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="CI tier: 1 in-process frontend + 2 backends, "
                        "short windows, full churn coverage")
    p.add_argument("--seconds", type=float, default=None,
                   help="steady-phase window (default 6, smoke 2)")
    p.add_argument("--clients", type=int, default=None,
                   help="closed-loop clients (default 4, smoke 2)")
    p.add_argument("--rows", type=int, default=8)
    p.add_argument("--frontends", type=int, default=2,
                   help="replicated edge processes (full mode)")
    p.add_argument("--phase-timeout", type=float, default=180.0)
    p.add_argument("--out", default=None,
                   help="JSON file to merge the multi_host section into "
                        "(created if missing)")
    args = p.parse_args(argv)
    if args.seconds is None:
        args.seconds = 2.0 if args.smoke else 6.0
    if args.clients is None:
        args.clients = 2 if args.smoke else 4

    t0 = time.time()
    mh = run_bench(args)
    mh["bench_seconds"] = round(time.time() - t0, 1)
    print(json.dumps({"multi_host": mh}, indent=2))

    if not mh["zero_failed_requests"]:
        print("fleet bench: FAILED REQUESTS DETECTED", file=sys.stderr)
        return 1
    if args.out:
        rec = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                rec = json.load(f)
        rec["multi_host"] = mh
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
        print(f"fleet bench: merged multi_host into {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
