"""Serving latency bench: p50/p99 through the HTTP server under
concurrent load — single ModelServer vs ServerGroup replicas, batching
on/off, and the rolling-update blip.

The measurement SessionGroup exists for (docs/docs_en/SessionGroup.md:
tail latency under concurrency, plus model updates without a serving
gap). Run:

    python tools/bench_serving.py [--groups 2,4] [--clients 8] \
        [--seconds 5] [--rows 8] [--out SERVING_BENCH.json]

Prints one JSON line per configuration:
    {"config": "group-2", "rps": ..., "p50_ms": ..., "p99_ms": ...,
     "stages": {"queue": {...}, "pad": {...}, "device": {...},
                "post": {...}, "e2e": {...}}}
(the `stages` breakdown is the server's own /v1/stats accounting for the
measured window) and, for the largest group, extra phases where a new
checkpoint lands mid-load and rolls across the replicas:
    {"config": "group-4+rolling-update", ..., "during_update_p99_ms": ...,
     "during_update_max_ms": ..., "model_version_advanced": true}

`--smoke` runs a tiny two-config pass (CI: compiles both the single and
group dispatch paths, lands one delta update mid-load, checks /v1/stats
over HTTP) and asserts structure, not timings.

On a TPU host run WITHOUT JAX_PLATFORMS=cpu to serve from the chip.
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build(tmp, emb_dim=16, steps=5):
    import jax.numpy as jnp
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = WDL(emb_dim=emb_dim, capacity=1 << 14, hidden=(128, 64),
                num_cat=8, num_dense=4)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=256, num_cat=8, num_dense=4,
                          vocab=5000, seed=11)
    for _ in range(steps):
        st, _ = tr.train_step(st, {k: jnp.asarray(v)
                                   for k, v in gen.batch().items()})
    ck = CheckpointManager(tmp, tr)
    # keep the returned state: save() clears the dirty bitmap, so later
    # incremental saves contain only rows actually touched since
    st, _ = ck.save(st)
    req = {k: v for k, v in gen.batch().items() if not k.startswith("label")}

    def save_next(mode: str = "full"):
        """Train a few more steps and land a NEW checkpoint (the rolling-
        update stimulus). mode="delta" writes an incremental checkpoint —
        the DeltaModelUpdate path: poll_updates replays touched rows onto
        the live state instead of a full reload."""
        nonlocal st
        for _ in range(3):
            st, _ = tr.train_step(st, {k: jnp.asarray(v)
                                       for k, v in gen.batch().items()})
        if mode == "delta":
            st, _ = ck.save_incremental(st)
        else:
            st, _ = ck.save(st)
        return int(st.step)

    # Prime the trainer-side incremental-save programs (dirty compaction
    # traces/compiles on first use): the co-located trainer is bench
    # STIMULUS, not the system under test — on this shared host its
    # first-save compiles would otherwise bleed into the measured serving
    # window. Production serving hosts don't run the trainer at all.
    save_next("delta")
    return model, req, save_next


def drive(port, payloads, seconds, clients, until_event=None):
    """Concurrent closed-loop clients; returns [(t_start, latency_s)]
    sorted by start time. Runs for `seconds`, extended while `until_event`
    (if given) is unset — the rolling-update phase must outlast the
    update. Any request failure aborts the bench loudly — silent drops
    would report flattering numbers from a broken server."""
    recs = []
    errors = []
    lock = threading.Lock()
    stop = time.monotonic() + seconds

    def keep_going():
        if errors:
            return False
        if time.monotonic() < stop:
            return True
        return until_event is not None and not until_event.is_set()

    def worker(i):
        body = payloads[i % len(payloads)]
        mine = []
        try:
            while keep_going():
                t0 = time.monotonic()
                r = urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{port}/v1/predict", data=body,
                        headers={"Content-Type": "application/json"},
                        method="POST",
                    ),
                    timeout=60,
                )
                r.read()
                mine.append((t0, time.monotonic() - t0))
        except Exception as e:
            with lock:
                errors.append(e)
        finally:
            with lock:
                recs.extend(mine)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise RuntimeError(f"{len(errors)} client(s) failed") from errors[0]
    if not recs:
        raise RuntimeError("no requests completed within the window")
    return sorted(recs)


def pct(lat, q):
    lat = sorted(lat)
    return lat[min(int(q * len(lat)), len(lat) - 1)]


def summarize(name, recs, seconds, clients, rows, extra=None, server=None):
    lat = [dt for _, dt in recs]
    out = {
        "config": name,
        "clients": clients,
        "rows_per_req": rows,
        "requests": len(lat),
        "rps": round(len(lat) / seconds, 1),
        "p50_ms": round(1e3 * pct(lat, 0.50), 2),
        "p90_ms": round(1e3 * pct(lat, 0.90), 2),
        "p99_ms": round(1e3 * pct(lat, 0.99), 2),
        "backend": __import__("jax").default_backend(),
    }
    if server is not None:
        # the server's own stage accounting for the measured window —
        # identical numbers to a live GET /v1/stats
        snap = server.stats_snapshot()
        out["stages"] = snap["stages"]
        out["batches"] = snap["batches"]
        out["model"] = snap["model"]
        if "replicas" in snap:
            out["replicas"] = snap["replicas"]
    out.update(extra or {})
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", default="2,4",
                    help="comma-separated ServerGroup replica counts")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=8,
                    help="rows per client request")
    ap.add_argument("--out", default=None,
                    help="also write the result list to this JSON file")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI pass: single + group-2, one delta update "
                         "mid-load, structural asserts (stats present, "
                         "version advanced, zero failed requests)")
    args = ap.parse_args()
    if args.smoke:
        args.groups, args.seconds, args.clients, args.rows = "2", 1.2, 4, 4
    groups = [int(g) for g in args.groups.split(",") if g]

    import numpy as np

    from deeprec_tpu.serving import (
        HttpServer, ModelServer, Predictor, ServerGroup,
    )

    with tempfile.TemporaryDirectory() as tmp:
        model, req, save_next = build(tmp)
        payloads = []
        for off in range(args.clients):
            sl = {k: np.asarray(v)[off * args.rows:(off + 1) * args.rows]
                  for k, v in req.items()}
            payloads.append(json.dumps(
                {"features": {k: v.tolist() for k, v in sl.items()}}
            ).encode())

        results = []
        # max_batch=1 disables cross-request coalescing — the "batching
        # off" baseline SessionGroup docs compare against.
        configs = [
            ("single-nobatch", lambda: ModelServer(
                Predictor(model, tmp), max_batch=1, max_wait_ms=0.0)),
            ("single", lambda: ModelServer(
                Predictor(model, tmp), max_batch=256, max_wait_ms=1.0)),
        ] + [
            (f"group-{g}", (lambda g=g: ServerGroup(
                model, tmp, replicas=g, max_batch=256, max_wait_ms=1.0)))
            for g in groups
        ]
        if args.smoke:
            configs = [c for c in configs if c[0] != "single-nobatch"]
        for name, make in configs:
            server = make()
            server.warmup({k: np.asarray(v)[:args.rows]
                           for k, v in req.items()})
            http = HttpServer(server, port=0).start()
            try:
                # settle, then measure (stats cover the measured window only)
                drive(http.port, payloads, 0.5, 2)
                server.stats.reset()
                recs = drive(http.port, payloads, args.seconds, args.clients)
                out = summarize(name, recs, args.seconds, args.clients,
                                args.rows, server=server)
                results.append(out)
                print(json.dumps(out), flush=True)
                if args.smoke:
                    check_smoke_config(out, http)

                if groups and name == f"group-{max(groups)}":
                    phases = [(save_next, "+rolling-update"),
                              (lambda: save_next("delta"), "+delta-update"),
                              # second delta runs entirely on warm compile
                              # caches — the serving-cadence steady state
                              (lambda: save_next("delta"),
                               "+delta-update-warm")]
                    if args.smoke:
                        phases = phases[1:2]  # one delta update is enough
                    for fn, label in phases:
                        results.append(rolling_update_phase(
                            server, http, payloads, args, name, fn,
                            label=label))
            finally:
                http.stop()
                server.close()
        if args.smoke:
            check_smoke_results(results, groups)
            print("bench_serving smoke OK", flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"results": results,
                           "protocol": vars(args)}, f, indent=1)
        return results


def check_smoke_config(out, http):
    """Structural asserts for one measured config: the stage breakdown is
    present and the SAME accounting is served live over /v1/stats."""
    for stage in ("queue", "pad", "device", "post", "e2e"):
        assert out["stages"][stage]["count"] > 0, (out["config"], stage)
    live = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{http.port}/v1/stats", timeout=10).read())
    assert set(live["stages"]) == set(out["stages"])
    assert live["model"]["version"] >= 0


def check_smoke_results(results, groups):
    by_name = {r["config"]: r for r in results}
    upd = by_name[f"group-{max(groups)}+delta-update"]
    assert upd["model_version_advanced"], upd
    assert upd["during_update_p99_ms"] is not None
    assert upd["model"]["updates"] >= 1


def rolling_update_phase(server, http, payloads, args, name, save_next,
                         label="+rolling-update"):
    """Measure the rolling-update blip: a new checkpoint lands mid-load
    and poll_updates rolls it across replicas while clients keep
    hammering. Reports steady vs during-update latency and asserts the
    model version actually advanced with zero failed requests (drive()
    raises on any failure)."""
    v0 = server.predictor.model_info().get("step")
    server.stats.reset()
    window = {}
    done = threading.Event()

    def updater():
        try:
            time.sleep(args.seconds / 3)
            step = save_next()
            t0 = time.monotonic()
            changed = server.predictor.poll_updates()
            window.update(t0=t0, t1=time.monotonic(), changed=changed,
                          new_step=step)
        except Exception as e:  # surfaced below — fail loudly, not KeyError
            window["error"] = e
        finally:
            done.set()

    th = threading.Thread(target=updater)
    t_begin = time.monotonic()
    th.start()
    recs = drive(http.port, payloads, args.seconds, args.clients,
                 until_event=done)
    th.join()
    elapsed = time.monotonic() - t_begin
    if "error" in window:
        raise RuntimeError("rolling-update phase failed") from window["error"]

    t0, t1 = window["t0"] - 0.25, window["t1"] + 0.25
    # classify by interval OVERLAP: a request in flight when the update
    # starts belongs to the update window even if it started before it
    during = [dt for ts, dt in recs if ts <= t1 and ts + dt >= t0]
    steady = [dt for ts, dt in recs if ts + dt < t0 or ts > t1]
    v1 = server.predictor.model_info().get("step")
    out = summarize(
        name + label, recs, elapsed, args.clients,
        args.rows, server=server,
        extra={
            "steady_p99_ms": (
                round(1e3 * pct(steady, 0.99), 2) if steady else None),
            "during_update_p99_ms": (
                round(1e3 * pct(during, 0.99), 2) if during else None),
            "during_update_max_ms": (
                round(1e3 * max(during), 2) if during else None),
            "update_window_ms": round(1e3 * (window["t1"] - window["t0"]), 1),
            "model_version_advanced": bool(window["changed"]) and v1 != v0,
        },
    )
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    main()
