"""Serving latency bench: p50/p99 through the HTTP server under
concurrent load, single ModelServer vs ServerGroup replicas.

The measurement SessionGroup exists for (docs/docs_en/SessionGroup.md:
tail-latency under concurrency). Run:

    python tools/bench_serving.py [--replicas 2] [--clients 8] \
        [--seconds 5] [--rows 8]

Prints one JSON line per configuration:
    {"config": "group-2", "rps": ..., "p50_ms": ..., "p99_ms": ...}

On a TPU host run WITHOUT JAX_PLATFORMS=cpu to serve from the chip.
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build(tmp, emb_dim=16, steps=5):
    import jax.numpy as jnp
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = WDL(emb_dim=emb_dim, capacity=1 << 14, hidden=(128, 64),
                num_cat=8, num_dense=4)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=256, num_cat=8, num_dense=4,
                          vocab=5000, seed=11)
    for _ in range(steps):
        st, _ = tr.train_step(st, {k: jnp.asarray(v)
                                   for k, v in gen.batch().items()})
    CheckpointManager(tmp, tr).save(st)
    req = {k: v for k, v in gen.batch().items() if not k.startswith("label")}
    return model, req


def drive(port, payloads, seconds, clients):
    """Concurrent closed-loop clients; returns sorted latencies (s).
    Any request failure aborts the bench loudly — silent drops would
    report flattering numbers from a broken server."""
    lat = []
    errors = []
    lock = threading.Lock()
    stop = time.monotonic() + seconds

    def worker(i):
        body = payloads[i % len(payloads)]
        mine = []
        try:
            while time.monotonic() < stop and not errors:
                t0 = time.monotonic()
                r = urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{port}/v1/predict", data=body,
                        headers={"Content-Type": "application/json"},
                        method="POST",
                    ),
                    timeout=60,
                )
                r.read()
                mine.append(time.monotonic() - t0)
        except Exception as e:
            with lock:
                errors.append(e)
        finally:
            with lock:
                lat.extend(mine)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise RuntimeError(f"{len(errors)} client(s) failed") from errors[0]
    if not lat:
        raise RuntimeError("no requests completed within the window")
    return sorted(lat)


def pct(lat, q):
    return lat[min(int(q * len(lat)), len(lat) - 1)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=8,
                    help="rows per client request")
    args = ap.parse_args()

    import numpy as np

    from deeprec_tpu.serving import (
        HttpServer, ModelServer, Predictor, ServerGroup,
    )

    with tempfile.TemporaryDirectory() as tmp:
        model, req = build(tmp)
        payloads = []
        for off in range(args.clients):
            sl = {k: np.asarray(v)[off * args.rows:(off + 1) * args.rows]
                  for k, v in req.items()}
            payloads.append(json.dumps(
                {"features": {k: v.tolist() for k, v in sl.items()}}
            ).encode())

        results = []
        configs = [
            ("single", lambda: ModelServer(
                Predictor(model, tmp), max_batch=256, max_wait_ms=1.0)),
            (f"group-{args.replicas}", lambda: ServerGroup(
                model, tmp, replicas=args.replicas, max_batch=256,
                max_wait_ms=1.0)),
        ]
        for name, make in configs:
            server = make()
            server.warmup({k: np.asarray(v)[:args.rows]
                           for k, v in req.items()})
            http = HttpServer(server, port=0).start()
            try:
                # settle, then measure
                drive(http.port, payloads, 0.5, 2)
                lat = drive(http.port, payloads, args.seconds, args.clients)
            finally:
                http.stop()
                server.close()
            out = {
                "config": name,
                "clients": args.clients,
                "rows_per_req": args.rows,
                "requests": len(lat),
                "rps": round(len(lat) / args.seconds, 1),
                "p50_ms": round(1e3 * pct(lat, 0.50), 2),
                "p90_ms": round(1e3 * pct(lat, 0.90), 2),
                "p99_ms": round(1e3 * pct(lat, 0.99), 2),
                "backend": __import__("jax").default_backend(),
            }
            results.append(out)
            print(json.dumps(out), flush=True)
        return results


if __name__ == "__main__":
    main()
