"""Serving latency bench: p50/p99 through the HTTP server under
concurrent load — single ModelServer vs ServerGroup replicas, the
multi-process socket tier, quantized row residency, the grouped
two-tower arm, and the rolling-update blip.

The measurement SessionGroup exists for (docs/docs_en/SessionGroup.md:
tail latency under concurrency, plus model updates without a serving
gap). Run:

    python tools/bench_serving.py [--groups 2,4] [--clients 8] \
        [--seconds 5] [--rows 8] [--processes 1,2,4] \
        [--quantize fp32,bf16,int8] [--grouped] [--out SERVING_BENCH.json]

Prints one JSON line per configuration:
    {"config": "group-2", "rps": ..., "p50_ms": ..., "p99_ms": ...,
     "stages": {"queue": {...}, "pad": {...}, "device": {...},
                "post": {...}, "e2e": {...}}}
(the `stages` breakdown is the server's own /v1/stats accounting for the
measured window) and, for the largest group, extra phases where a new
checkpoint lands mid-load and rolls across the replicas:
    {"config": "group-4+rolling-update", ..., "during_update_p99_ms": ...,
     "during_update_max_ms": ..., "model_version_advanced": true}

The extra grids:
  * `--processes 1,2,4` — the socket-tier scale-out (serving/frontend.py):
    N backend serving PROCESSES behind one Frontend + HTTP edge, with a
    delta update broadcast mid-load at the largest N. Records measured
    rps per arm plus a CPU-split Amdahl model (frontend vs backend CPU
    seconds per request) — on a host with fewer cores than processes the
    measured arms are core-bound and the model carries the scaling claim
    (`cpu_limited: true`; `roofline.py --assert-serving` gates the model
    there and the measurement on capable hosts).
  * `--quantize fp32,bf16,int8` — single-process arms serving the same
    checkpoint at each residency; int8 additionally replays a delta
    chain under a trace guard (steady-state serving compiles must be 0)
    and records measured-vs-modeled residency bytes.
  * `--grouped` — the DSSM two-tower arm: `<user, N items>` requests
    with and without `group_users` (sample-aware user-tower reuse);
    headline metric is candidates/sec.
  * `--compute-reuse` — the frontend compute-reuse arm (serving/reuse.py):
    a persistent zipf(`--user-zipf`) population of `--users` distinct
    request payloads driven closed-loop against the same server with the
    version-keyed answer cache OFF then ON (`--reuse-mb`). Records hit
    rates, effective qps per arm (`roofline.py --assert-reuse` gates the
    ≥2× factor), a mid-load delta publish (hit-rate dip + recovery with
    zero failed requests), the cache-on/off/no_cache bit-identity probe,
    and the steady-window compile count under a trace guard.

`--smoke` runs a tiny pass over every grid (CI: group dispatch, a
2-process socket tier + int8 + grouped arms, one delta update mid-load,
/v1/stats over HTTP) and asserts structure, not timings.

On a TPU host run WITHOUT JAX_PLATFORMS=cpu to serve from the chip.
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


# one definition feeds both the in-process model and the backend CLI
# (spawn_backends ships it as --model-json), so the socket-tier arms serve
# exactly the checkpoint build() trained
WDL_ARGS = {"emb_dim": 16, "capacity": 1 << 14, "hidden": [128, 64],
            "num_cat": 8, "num_dense": 4}

# The socket-tier arm serves a production-width ranking tower (4096/2048
# vs the PR 5 toy's 128/64): process scale-out is the regime where
# backend compute dominates the routing edge. With the tiny model,
# efficient coalescing leaves the GIL-bound frontend as the ~1.3
# ms/request ceiling and no process count helps — measured here so the
# ceiling is recorded, not hidden. The legacy single/group arms keep the
# PR 5 model untouched for protocol continuity.
SCALE_ARGS = {"emb_dim": 16, "capacity": 1 << 14, "hidden": [4096, 2048],
              "num_cat": 8, "num_dense": 4}


def build(tmp, steps=5, margs=None):
    import jax.numpy as jnp
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    kw = dict(margs or WDL_ARGS)
    model = WDL(emb_dim=kw["emb_dim"], capacity=kw["capacity"],
                hidden=tuple(kw["hidden"]), num_cat=kw["num_cat"],
                num_dense=kw["num_dense"])
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=256, num_cat=8, num_dense=4,
                          vocab=5000, seed=11)
    for _ in range(steps):
        st, _ = tr.train_step(st, {k: jnp.asarray(v)
                                   for k, v in gen.batch().items()})
    ck = CheckpointManager(tmp, tr)
    # keep the returned state: save() clears the dirty bitmap, so later
    # incremental saves contain only rows actually touched since
    st, _ = ck.save(st)
    req = {k: v for k, v in gen.batch().items() if not k.startswith("label")}

    def save_next(mode: str = "full"):
        """Train a few more steps and land a NEW checkpoint (the rolling-
        update stimulus). mode="delta" writes an incremental checkpoint —
        the DeltaModelUpdate path: poll_updates replays touched rows onto
        the live state instead of a full reload."""
        nonlocal st
        for _ in range(3):
            st, _ = tr.train_step(st, {k: jnp.asarray(v)
                                       for k, v in gen.batch().items()})
        if mode == "delta":
            st, _ = ck.save_incremental(st)
        else:
            st, _ = ck.save(st)
        return int(st.step)

    # Prime the trainer-side incremental-save programs (dirty compaction
    # traces/compiles on first use): the co-located trainer is bench
    # STIMULUS, not the system under test — on this shared host its
    # first-save compiles would otherwise bleed into the measured serving
    # window. Production serving hosts don't run the trainer at all.
    save_next("delta")
    return model, req, save_next


def drive(port, payloads, seconds, clients, until_event=None,
          thread_cpu=None):
    """Concurrent closed-loop clients; returns [(t_start, latency_s)]
    sorted by start time. Runs for `seconds`, extended while `until_event`
    (if given) is unset — the rolling-update phase must outlast the
    update. Any request failure aborts the bench loudly — silent drops
    would report flattering numbers from a broken server.

    `thread_cpu` (a list) collects each client thread's own CPU seconds:
    the scale-out arms subtract the LOAD GENERATOR's CPU from the bench
    process's, so the recorded frontend-tier CPU split describes the
    serving tier, not the drivers (which are remote in production)."""
    recs = []
    errors = []
    lock = threading.Lock()
    stop = time.monotonic() + seconds

    def keep_going():
        if errors:
            return False
        if time.monotonic() < stop:
            return True
        return until_event is not None and not until_event.is_set()

    def worker(i):
        body = payloads[i % len(payloads)]
        mine = []
        cpu0 = time.thread_time()
        try:
            while keep_going():
                t0 = time.monotonic()
                r = urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{port}/v1/predict", data=body,
                        headers={"Content-Type": "application/json"},
                        method="POST",
                    ),
                    timeout=60,
                )
                r.read()
                mine.append((t0, time.monotonic() - t0))
        except Exception as e:
            with lock:
                errors.append(e)
        finally:
            with lock:
                recs.extend(mine)
                if thread_cpu is not None:
                    thread_cpu.append(time.thread_time() - cpu0)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise RuntimeError(f"{len(errors)} client(s) failed") from errors[0]
    if not recs:
        raise RuntimeError("no requests completed within the window")
    return sorted(recs)


def pct(lat, q):
    lat = sorted(lat)
    return lat[min(int(q * len(lat)), len(lat) - 1)]  # noqa: DRT002 — host latency list percentile (name-collision reachability)


def summarize(name, recs, seconds, clients, rows, extra=None, server=None):
    lat = [dt for _, dt in recs]
    out = {
        "config": name,
        "clients": clients,
        "rows_per_req": rows,
        "requests": len(lat),
        "rps": round(len(lat) / seconds, 1),
        "p50_ms": round(1e3 * pct(lat, 0.50), 2),
        "p90_ms": round(1e3 * pct(lat, 0.90), 2),
        "p99_ms": round(1e3 * pct(lat, 0.99), 2),
        "backend": __import__("jax").default_backend(),
    }
    if server is not None:
        # the server's own stage accounting for the measured window —
        # identical numbers to a live GET /v1/stats
        snap = server.stats_snapshot()
        out["stages"] = snap["stages"]
        out["batches"] = snap["batches"]
        out["model"] = snap["model"]
        if "replicas" in snap:
            out["replicas"] = snap["replicas"]
    out.update(extra or {})
    return out


def make_payloads(req, clients, rows):
    """One JSON body per closed-loop client, sliced from the example
    request (the PR 5 drive protocol)."""
    payloads = []
    for off in range(clients):
        sl = {k: np.asarray(v)[off * rows:(off + 1) * rows]
              for k, v in req.items()}
        payloads.append(json.dumps(
            {"features": {k: v.tolist() for k, v in sl.items()}}
        ).encode())
    return payloads


def _backend_cpu_seconds(fe) -> float:
    """Sum of the backend processes' CPU seconds (each BackendServer
    reports `time.process_time()` in its STAT frame)."""
    total = 0.0
    for m in fe.stats_snapshot()["members"]:
        total += m.get("stats", {}).get("process_cpu_seconds", 0.0)
    return total


def scale_out_grid(args, results):
    """The socket-tier arms: N backend serving processes behind one
    Frontend + HTTP edge. Measures rps per N, the frontend/backend CPU
    split per request, and (at the largest N) a delta update broadcast
    mid-load. Returns the `scale_out` section of the bench JSON: on a
    host with fewer cores than processes the measured arms are
    core-bound, so the CPU-split Amdahl model carries the scaling claim
    (`cpu_limited: true` — `roofline.py --assert-serving` gates the
    model there, the measurement on capable hosts)."""
    import os
    import tempfile as _tempfile

    from deeprec_tpu.serving import Frontend, HttpServer, spawn_backends

    counts = sorted({int(x) for x in args.processes.split(",") if x})
    host_cores = len(os.sched_getaffinity(0))
    biggest = max(counts)
    section = {
        "host_cores": host_cores,
        # Linear MEASURED scaling needs a core per backend, one for the
        # frontend/HTTP edge, and one for the in-process closed-loop
        # drivers — gating the measurement on a host that is merely
        # "barely enough" cores would flake, which is exactly what the
        # modeled fallback exists for.
        "cpu_limited": host_cores < biggest + 2,
        "arms": {},
    }
    mj = json.dumps(SCALE_ARGS)
    scale_dir = _tempfile.mkdtemp(prefix="deeprec-scale-")
    model, req, save_next = build(scale_dir, margs=SCALE_ARGS)
    payloads = make_payloads(req, args.clients, args.rows)
    for n in counts:
        procs, addrs = spawn_backends(
            n, ckpt=scale_dir, model="wdl", model_json=mj, poll_secs=0.0,
            max_batch=256, max_wait_ms=1.0)
        fe = Frontend(addrs, model, poll_backends=True)
        http = HttpServer(fe, port=0).start()
        try:
            # Deterministic per-backend bucket-ladder warm: EVERY member
            # compiles every coalescing bucket the measured concurrency
            # can produce, or the window measures XLA compilation as
            # backend load (round-robin settle traffic doesn't guarantee
            # every member sees every bucket).
            example = {k: np.asarray(v)[:1] for k, v in req.items()}
            top = 8
            while top < min(256, args.clients * args.rows):
                top <<= 1
            ladder, b = [], 8
            while b < top:
                ladder.append(b)
                b <<= 1
            ladder.append(top)
            fe.warmup(example, ladder=ladder)
            drive(http.port, payloads, 0.5, args.clients)  # settle
            fe.stats.reset()
            bcpu0 = _backend_cpu_seconds(fe)
            client_cpu = []
            fcpu0 = time.process_time()
            recs = drive(http.port, payloads, args.seconds, args.clients,
                         thread_cpu=client_cpu)
            fcpu1 = time.process_time()
            bcpu1 = _backend_cpu_seconds(fe)
            out = summarize(f"procs-{n}", recs, args.seconds, args.clients,
                            args.rows, server=fe)
            nreq = max(len(recs), 1)
            out["processes"] = n
            out["host_cores"] = host_cores
            # tier CPU only: the closed-loop drivers' own CPU is load
            # generation, not serving (remote in production) — subtract it
            out["frontend_cpu_per_req_ms"] = round(
                1e3 * (fcpu1 - fcpu0 - sum(client_cpu)) / nreq, 4)
            out["client_cpu_per_req_ms"] = round(
                1e3 * sum(client_cpu) / nreq, 4)
            out["backend_cpu_per_req_ms"] = round(
                1e3 * (bcpu1 - bcpu0) / nreq, 4)
            results.append(out)
            print(json.dumps(out), flush=True)
            section["arms"][str(n)] = {
                "rps": out["rps"],
                "frontend_cpu_per_req_ms": out["frontend_cpu_per_req_ms"],
                "backend_cpu_per_req_ms": out["backend_cpu_per_req_ms"],
            }
            if n == biggest:
                results.append(rolling_update_phase(
                    fe, http, payloads, args, f"procs-{n}",
                    lambda: save_next("delta"), label="+delta-update"))
        finally:
            http.stop()
            fe.close()
            for p in procs:
                p.kill()
            for p in procs:
                p.wait()
    one = section["arms"].get("1")
    if one:
        s_f = one["frontend_cpu_per_req_ms"]
        s_b = one["backend_cpu_per_req_ms"]
        # Amdahl over the CPU split: the frontend's per-request CPU is the
        # serial term, the backends' divides by N. Modeled rps(N) =
        # 1 / max(serial, parallel / N) — what this tier does the moment
        # each process owns a core.
        modeled = {
            str(n): (round(1e3 / max(s_f, s_b / n), 1)
                     if max(s_f, s_b) > 0 else None)
            for n in counts
        }
        section["modeled"] = {
            "rps": modeled,
            "speedup": {
                k: (round(v / modeled["1"], 2)
                    if v and modeled.get("1") else None)
                for k, v in modeled.items()
            },
            "frontend_cpu_per_req_ms": s_f,
            "backend_cpu_per_req_ms": s_b,
        }
        section["measured_speedup"] = {
            k: round(a["rps"] / one["rps"], 2)
            for k, a in section["arms"].items()
        }
    import shutil

    shutil.rmtree(scale_dir, ignore_errors=True)
    return section


def quantize_arms(args, tmp, model, req, payloads, save_next, results):
    """Residency arms: serve the SAME checkpoint at fp32/bf16/int8 in a
    single-process ModelServer. Each arm records measured + modeled
    residency bytes; non-fp32 arms additionally replay a delta chain
    under a trace guard — the zero-retrace serving contract extended to
    the quantized import path (steady-state compiles must be 0)."""
    from deeprec_tpu.analysis.trace_guard import trace_guard
    from deeprec_tpu.serving import HttpServer, ModelServer, Predictor

    section = {}
    for q in [x for x in args.quantize.split(",") if x]:
        pred = Predictor(model, tmp, quantize=q)
        server = ModelServer(pred, max_batch=256, max_wait_ms=1.0)
        server.warmup({k: np.asarray(v)[:args.rows]
                       for k, v in req.items()})
        http = HttpServer(server, port=0).start()
        try:
            drive(http.port, payloads, 0.5, 2)
            server.stats.reset()
            recs = drive(http.port, payloads, args.seconds, args.clients)
            out = summarize(f"quant-{q}", recs, args.seconds, args.clients,
                            args.rows, server=server)
            out["residency"] = pred.residency_info()
            # steady-state delta replay on this residency: the first
            # replay + probe pad every cache, then the guarded replay +
            # predict must compile 0 (the PR 5 zero-retrace contract on
            # the quantized import path)
            probe = {k: np.asarray(v)[:args.rows] for k, v in req.items()}
            save_next("delta")
            pred.poll_updates()
            pred.predict(probe)
            save_next("delta")
            with trace_guard(max_compiles=None) as g:
                pred.poll_updates()
                pred.predict(probe)
            out["serving_compiles"] = g.compiles
            results.append(out)
            print(json.dumps(out), flush=True)
            section[q] = {
                "rps": out["rps"],
                "residency": out["residency"],
                "serving_compiles": out["serving_compiles"],
            }
        finally:
            http.stop()
            server.close()
    return section


def build_two_tower(tmp, steps=4):
    """Train the modelzoo DSSM briefly and checkpoint it — the two-tower
    stimulus of the grouped arm. The towers are ASYMMETRIC (8 user
    features through a 512-wide tower vs 2 item features through a
    128-wide one): the production retrieval shape — user side encodes
    the heavy behavior context, item side is a cheap projection — and
    the regime where scoring N candidates per user-tower evaluation
    pays N×, per PAPERS' asymmetric-data-flow analysis."""
    import jax.numpy as jnp
    import optax

    from deeprec_tpu.data import SyntheticTwoTower
    from deeprec_tpu.models import DSSM
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = DSSM(emb_dim=16, capacity=1 << 14, num_user_feats=8,
                 num_item_feats=2, hidden=(128, 64),
                 user_hidden=(4096, 512, 64))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=256, num_user=8, num_item=2,
                            vocab=20000, seed=23)
    for _ in range(steps):
        st, _ = tr.train_step(st, {k: jnp.asarray(v)
                                   for k, v in gen.batch().items()})
    CheckpointManager(tmp, tr).save(st)
    base = {k: np.asarray(v) for k, v in gen.batch().items()
            if not k.startswith("label")}
    return model, base


def grouped_arms(args, results):
    """The N-candidate user-tower-reuse arm: `<user, N items>` requests
    through the micro-batcher with and without `group_users`. Headline
    metric is candidates/sec — sample-aware compression runs the user
    tower once per distinct user per coalesced batch, so the grouped arm
    scores the same candidates for a fraction of the tower FLOPs."""
    import tempfile as _tempfile

    from deeprec_tpu.serving import HttpServer, ModelServer, Predictor

    R = args.grouped_rows
    with _tempfile.TemporaryDirectory() as tmp2:
        model, base = build_two_tower(tmp2)
        B = len(next(iter(base.values())))

        def items_slice(v, u):
            start = (u * R) % max(1, B - R + 1)
            return v[start:start + R]

        payloads = {}
        for grouped in (False, True):
            per_client = []
            for u in range(args.clients):
                req = {}
                for k, v in base.items():
                    rows = (np.repeat(v[u:u + 1], R, axis=0)
                            if k in model.user_feats else items_slice(v, u))
                    req[k] = rows
                body = {"features": {k: x.tolist() for k, x in req.items()}}
                if grouped:
                    body["group_users"] = True
                per_client.append(json.dumps(body).encode())
            payloads[grouped] = per_client
        section = {"rows_per_request": R}
        pred = Predictor(model, tmp2)
        server = ModelServer(pred, max_batch=max(256, 4 * R),
                             max_wait_ms=1.0)
        example = {k: v[:R] for k, v in base.items()}
        server.warmup(example, group_users=True)
        # Warm the grouped (row-bucket, group-bucket) grid the coalesced
        # load will hit: k of the `clients` distinct users per batch →
        # k·R rows with k groups. Without this the measured window pays
        # the compile storms the bucket ladder exists to prevent.
        for k in range(1, args.clients + 1):
            batch = {}
            for name, v in base.items():
                if name in model.user_feats:
                    rows = np.repeat(v[:k], R, axis=0)
                else:
                    rows = np.concatenate(
                        [items_slice(v, u) for u in range(k)])
                batch[name] = rows
            pred.predict(batch, group_users=True)
            pred.predict(batch)
        http = HttpServer(server, port=0).start()
        try:
            for grouped in (False, True):
                name = ("two-tower-grouped" if grouped
                        else "two-tower-ungrouped")
                drive(http.port, payloads[grouped], 0.5, 2)
                server.stats.reset()
                recs = drive(http.port, payloads[grouped], args.seconds,
                             args.clients)
                out = summarize(name, recs, args.seconds, args.clients, R,
                                server=server)
                out["candidates_per_sec"] = round(out["rps"] * R, 1)
                results.append(out)
                print(json.dumps(out), flush=True)
                section["grouped_cps" if grouped else "ungrouped_cps"] = (
                    out["candidates_per_sec"])
        finally:
            http.stop()
            server.close()
        if section.get("ungrouped_cps"):
            section["factor"] = round(
                section["grouped_cps"] / section["ungrouped_cps"], 2)
        return section


def user_payload(req, u, rows):
    """One user's persistent request features: a `rows`-slice of the
    example batch with a per-user perturbation on the float (dense)
    columns and a per-user roll of the integer (categorical) ones —
    every user owns a DISTINCT fingerprint (the reuse-cache key) while
    every payload keeps the SAME shape, so the whole population shares
    one compile bucket."""
    feats = {}
    for k, v in req.items():
        a = np.asarray(v)
        if np.issubdtype(a.dtype, np.floating):
            feats[k] = a[:rows] + a.dtype.type(u) * a.dtype.type(1e-3)
        else:
            feats[k] = np.roll(a, u, axis=0)[:rows]
    return feats


def make_user_pool(req, users, rows):
    """The zipf population: one JSON body per user, rank == user id
    (rank 0 is the hottest user under the zipf sampler)."""
    return [json.dumps({"features": {
        k: v.tolist() for k, v in user_payload(req, u, rows).items()
    }}).encode() for u in range(users)]


def drive_sampled(port, pool, probs, seconds, clients, seed=0,
                  until_event=None):
    """Closed-loop clients that SAMPLE a payload from `pool` per request
    with probabilities `probs` (the zipf draw) instead of pinning one
    body per client — the reuse arms need the request stream itself to
    carry the popularity skew. Same contract as drive(): any failure
    aborts loudly, returns [(t_start, latency_s)] sorted by start."""
    recs = []
    errors = []
    lock = threading.Lock()
    stop = time.monotonic() + seconds

    def keep_going():
        if errors:
            return False
        if time.monotonic() < stop:
            return True
        return until_event is not None and not until_event.is_set()

    def worker(i):
        rng = np.random.default_rng(seed + i)
        mine = []
        try:
            while keep_going():
                body = pool[int(rng.choice(len(pool), p=probs))]
                t0 = time.monotonic()
                r = urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{port}/v1/predict", data=body,
                        headers={"Content-Type": "application/json"},
                        method="POST",
                    ),
                    timeout=60,
                )
                r.read()
                mine.append((t0, time.monotonic() - t0))
        except Exception as e:
            with lock:
                errors.append(e)
        finally:
            with lock:
                recs.extend(mine)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise RuntimeError(f"{len(errors)} client(s) failed") from errors[0]
    if not recs:
        raise RuntimeError("no requests completed within the window")
    return sorted(recs)


def _reuse_counts(server):
    s = server.stats_snapshot()["reuse"]["predict"]
    return int(s["hits"]), int(s["misses"])


def _hit_rate(after, before):
    dh, dm = after[0] - before[0], after[1] - before[1]
    return round(dh / max(dh + dm, 1), 4)


def compute_reuse_arms(args, results):
    """The frontend compute-reuse arms (JSON 'compute_reuse', gated by
    roofline.py --assert-reuse): a persistent zipf(--user-zipf)
    population of --users distinct payloads driven closed-loop over HTTP
    against the SAME model/protocol with the version-keyed answer cache
    off, then on. The cache-on arm additionally:

      * measures its steady window under a trace guard (a cache hit must
        never trace — steady compiles are the DRT001 contract, 0);
      * lands a delta publish MID-LOAD and snapshots the hit rate before
        the swap, in the window right after (the invalidation dip — a
        version swap drops every old-version entry, never serves one),
        and over the remainder (recovery), with zero failed requests;
      * probes bit-identity: a cold miss, the hit that follows, and a
        forced `no_cache` re-eval must return byte-identical scores at
        one version — the cache is a pure memo, never an approximation.

    The arms serve the production-width SCALE_ARGS tower, not the PR 5
    toy: compute reuse is the regime where tower compute dominates the
    HTTP/parse constant both arms share (same rationale as the
    socket-tier grid) — with the toy model the python client stack caps
    both arms and the factor measures urllib, not reuse. The modeled
    speedup (ops/traffic.py serving_reuse_speedup) is recorded twice:
    the zero-hit-cost ceiling, and the factor at the MEASURED hit cost
    (cache-on p50 over cache-off p50) — the latter must track the
    measured factor or the model drifted."""
    import shutil
    import tempfile as _tempfile

    from deeprec_tpu.analysis.trace_guard import trace_guard
    from deeprec_tpu.ops.traffic import (
        serving_reuse_speedup, zipf_expected_hit_rate,
    )
    from deeprec_tpu.serving import HttpServer, ModelServer, Predictor

    users, alpha, rows = args.users, args.user_zipf, args.rows
    cap = int(args.reuse_mb * (1 << 20))
    reuse_dir = _tempfile.mkdtemp(prefix="deeprec-reuse-")
    model, req, save_next = build(reuse_dir, margs=SCALE_ARGS)
    pool = make_user_pool(req, users, rows)
    ranks = np.arange(1, users + 1, dtype=np.float64) ** -float(alpha)
    probs = ranks / ranks.sum()
    section = {
        "users": users,
        "zipf_alpha": alpha,
        "rows_per_request": rows,
        "capacity_bytes": cap,
        "arms": {},
    }

    def sweep(port):
        # touch EVERY user once so the population is fully resident
        # before any measured window — the dip/recovery contrast must
        # come from the version swap, not from cold tail users
        for body in pool:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/predict", data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST"),
                timeout=60).read()

    for arm, cache_bytes in (("cache_off", 0), ("cache_on", cap)):
        pred = Predictor(model, reuse_dir)
        # max_batch bounds the warmup bucket ladder: the measured
        # concurrency coalesces at most clients*rows rows, and each
        # extra bucket is one more XLA compile of the wide tower
        mb = 8
        while mb < min(256, args.clients * rows):
            mb <<= 1
        server = ModelServer(pred, max_batch=mb, max_wait_ms=1.0,
                             reuse_cache_bytes=cache_bytes)
        server.warmup({k: np.asarray(v)[:rows] for k, v in req.items()})
        http = HttpServer(server, port=0).start()
        try:
            # prime the delta-replay programs before any guarded window
            # (same discipline as the quantized arm): the publish phase
            # below must measure invalidation, not first-replay compiles
            save_next("delta")
            pred.poll_updates()
            sweep(http.port)
            drive_sampled(http.port, pool, probs, 0.4, args.clients)
            server.stats.reset()
            c0 = _reuse_counts(server) if cache_bytes else None
            with trace_guard(max_compiles=None) as g:
                recs = drive_sampled(http.port, pool, probs, args.seconds,
                                     args.clients)
            out = summarize(f"reuse-{arm}", recs, args.seconds,
                            args.clients, rows, server=server)
            out["steady_compiles"] = g.compiles
            arm_rec = {
                "rps": out["rps"],
                "p50_ms": out["p50_ms"],
                "p99_ms": out["p99_ms"],
                "steady_compiles": g.compiles,
            }
            if cache_bytes:
                snap = server.stats_snapshot()["reuse"]
                arm_rec["hit_rate"] = _hit_rate(_reuse_counts(server), c0)
                arm_rec["memo_shared"] = snap["memo_shared"]
                arm_rec["occupancy_bytes"] = snap["predict"][
                    "occupancy_bytes"]
                arm_rec["entries"] = snap["predict"]["entries"]
                out["reuse"] = snap["predict"]
                section["hit_rate"] = arm_rec["hit_rate"]
                section["steady_compiles"] = g.compiles
                section["occupancy_within_capacity"] = (
                    snap["predict"]["occupancy_bytes"] <= cap)

                # ---- mid-load delta publish: dip + recovery ----------
                window = {}
                done = threading.Event()

                def updater():
                    try:
                        time.sleep(args.seconds / 3)
                        window["pre"] = _reuse_counts(server)
                        step = save_next("delta")
                        changed = pred.poll_updates()
                        window["pub"] = _reuse_counts(server)
                        window["changed"] = changed
                        window["new_step"] = step
                        time.sleep(max(0.25, args.seconds / 6))
                        window["dip"] = _reuse_counts(server)
                    except Exception as e:
                        window["error"] = e
                    finally:
                        done.set()

                p0 = _reuse_counts(server)
                th = threading.Thread(target=updater)
                th.start()
                drive_sampled(http.port, pool, probs, args.seconds,
                              args.clients, seed=101, until_event=done)
                th.join()
                if "error" in window:
                    raise RuntimeError("reuse publish phase failed") \
                        from window["error"]
                # a dedicated recovery window: the updater's train+save
                # can eat the tail of the mid-load drive, so the
                # post-dip rate gets its own guaranteed request stream
                drive_sampled(http.port, pool, probs,
                              max(0.4, args.seconds / 3), args.clients,
                              seed=202)
                p1 = _reuse_counts(server)
                inval = server.stats_snapshot()["reuse"]["predict"][
                    "invalidations"]
                section["publish"] = {
                    "pre_hit_rate": _hit_rate(window["pre"], p0),
                    "dip_hit_rate": _hit_rate(window["dip"],
                                              window["pub"]),
                    "recovered_hit_rate": _hit_rate(p1, window["dip"]),
                    "invalidations": inval,
                    "version_advanced": bool(window["changed"]),
                }

                # ---- bit-identity probe: miss, hit, forced re-eval ---
                probe = user_payload(req, users + 7, rows)
                r1, v1 = server.request_versioned(probe)
                r2, v2 = server.request_versioned(probe)
                r3, v3 = server.request_versioned(probe, no_cache=True)
                section["bit_identical"] = bool(
                    v1 == v2 == v3
                    and np.array_equal(np.asarray(r1), np.asarray(r2))
                    and np.array_equal(np.asarray(r1), np.asarray(r3)))
            section["arms"][arm] = arm_rec
            results.append(out)
            print(json.dumps(out), flush=True)
        finally:
            http.stop()
            server.close()

    shutil.rmtree(reuse_dir, ignore_errors=True)
    off, on = section["arms"]["cache_off"], section["arms"]["cache_on"]
    section["effective_qps_factor"] = round(
        on["rps"] / max(off["rps"], 1e-9), 2)
    hr = section.get("hit_rate", 0.0)
    # hit cost relative to a full eval, as the client saw it: the
    # cache-on arm's p50 is ~all hits, the off arm's all real evals
    c = min(on["p50_ms"] / max(off["p50_ms"], 1e-9), 0.999)
    section["modeled"] = {
        "zipf_hit_rate": round(zipf_expected_hit_rate(
            users=users, alpha=alpha, resident=users), 4),
        "speedup_ceiling": round(
            serving_reuse_speedup(hit_rate=min(hr, 0.999)), 2),
        "speedup_at_measured_hit_cost": round(serving_reuse_speedup(
            hit_rate=min(hr, 0.999), hit_cost_ratio=c), 2),
        "hit_cost_ratio": round(c, 4),
    }
    # drive()/drive_sampled() abort the whole bench on ANY failed
    # request, so a completed section IS the zero-failures assertion
    section["zero_failed_requests"] = True
    print(json.dumps({"config": "compute-reuse", **{
        k: v for k, v in section.items() if k != "arms"}}), flush=True)
    return section


def obs_overhead_section(args, tmp, model, req, payloads):
    """Telemetry-plane cost on the serving path (JSON 'obs_overhead',
    gated by roofline.py --assert-obs): one single-process server driven
    with the obs plane ON (registry-backed stage histograms + counters,
    live /metrics scrape) and once with DEEPREC_OBS=off (plain
    LatencyHistograms), plus a deterministic per-record microbench.
    `overhead_pct` — the gated number — is MODELED: per-record cost ×
    obs records per request over the measured p50 latency (wall-clock
    rps arms on a shared CI box are noisier than any honest overhead
    bound; they are recorded for eyeballs). The /metrics parse check is
    a REAL scrape of the live endpoint."""
    from deeprec_tpu.obs import metrics as om
    from deeprec_tpu.serving import HttpServer, ModelServer, Predictor

    seconds = min(args.seconds, 2.0)
    section = {"arms": {}}

    def arm(enabled):
        om.set_metrics_enabled(enabled)
        try:
            pred = Predictor(model, tmp)
            server = ModelServer(pred, max_batch=256, max_wait_ms=1.0)
            server.warmup({k: np.asarray(v)[:args.rows]
                           for k, v in req.items()})
            http = HttpServer(server, port=0).start()
            try:
                drive(http.port, payloads, 0.4, 2)  # settle
                server.stats.reset()
                recs = drive(http.port, payloads, seconds, args.clients)
                lat = [dt for _, dt in recs]
                out = {
                    "rps": round(len(lat) / seconds, 1),
                    "p50_ms": round(1e3 * pct(lat, 0.50), 3),
                }
                if enabled:
                    text = urllib.request.urlopen(
                        f"http://127.0.0.1:{http.port}/metrics",
                        timeout=10).read().decode()
                    parsed = om.parse_prometheus(text)
                    names = {k[0] for k in parsed}
                    section["metrics_endpoint"] = {
                        "parsed": True,
                        "series": len(parsed),
                        "has_stage_histogram":
                            "deeprec_serving_stage_seconds_bucket" in names,
                        "has_queue_depth":
                            "deeprec_serving_queue_depth" in names,
                    }
                return out
            finally:
                http.stop()
                server.close()
        finally:
            om.set_metrics_enabled(None)

    section["arms"]["on"] = arm(True)
    section["arms"]["off"] = arm(False)
    on, off = section["arms"]["on"], section["arms"]["off"]
    section["measured_overhead_pct"] = round(
        max(0.0, off["rps"] / max(on["rps"], 1e-9) - 1) * 100, 3)

    reg = om.MetricsRegistry()
    h = reg.histogram("bench_obs_h", "")
    c = reg.counter("bench_obs_c", "")
    N = 5000
    t0 = time.perf_counter()
    for _ in range(N):
        h.record(1e-3)
        c.inc()
    per_record_ns = (time.perf_counter() - t0) / (2 * N) * 1e9
    # per request: 5 stage records + batch counters (3 incs amortized
    # over the coalesced batch) + e2e bookkeeping ≈ 9 registry ops
    ops_per_request = 9.0
    section["per_record_ns"] = round(per_record_ns, 1)
    section["ops_per_request"] = ops_per_request
    section["overhead_pct"] = round(
        100.0 * ops_per_request * per_record_ns / (on["p50_ms"] * 1e6), 5)
    print(json.dumps({"config": "obs-overhead", **section}), flush=True)
    return section


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", default="2,4",
                    help="comma-separated ServerGroup replica counts")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=8,
                    help="rows per client request")
    ap.add_argument("--processes", default="",
                    help="comma-separated backend PROCESS counts for the "
                         "socket-tier grid (e.g. 1,2,4; empty = skip)")
    ap.add_argument("--quantize", default="",
                    help="comma-separated residency arms (fp32,bf16,int8; "
                         "empty = skip)")
    ap.add_argument("--grouped", action="store_true",
                    help="run the DSSM two-tower grouped/ungrouped arm")
    ap.add_argument("--grouped-rows", type=int, default=128,
                    help="candidate items per <user, N items> request")
    ap.add_argument("--compute-reuse", action="store_true",
                    help="run the zipf compute-reuse arms (answer cache "
                         "off vs on; serving/reuse.py)")
    ap.add_argument("--user-zipf", type=float, default=1.1,
                    help="zipf exponent of the persistent user "
                         "population driving the reuse arms")
    ap.add_argument("--users", type=int, default=64,
                    help="distinct users (distinct request fingerprints) "
                         "in the zipf population")
    ap.add_argument("--reuse-mb", type=float, default=64.0,
                    help="answer-cache budget (MiB) for the cache-on arm")
    ap.add_argument("--out", default=None,
                    help="also write the result list to this JSON file")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI pass: group-2 + a 2-process socket tier "
                         "+ int8 + grouped arms, one delta update mid-load, "
                         "structural asserts (stats present, version "
                         "advanced, zero failed requests)")
    args = ap.parse_args()
    if args.smoke:
        args.groups, args.seconds, args.clients, args.rows = "2", 1.2, 4, 4
        args.processes, args.quantize = "1,2", "int8"
        # grouped arm keeps the full per-request candidate count: the
        # compressed-vs-plain ratio is the contract the serving gate
        # pins, and it only exists where the user tower dominates
        args.grouped, args.grouped_rows = True, 128
        # reuse arm: a smaller population keeps the full-coverage sweep
        # cheap while the zipf head still dominates the stream
        args.compute_reuse, args.users = True, 32
    groups = [int(g) for g in args.groups.split(",") if g]

    from deeprec_tpu.serving import (
        HttpServer, ModelServer, Predictor, ServerGroup,
    )

    with tempfile.TemporaryDirectory() as tmp:
        model, req, save_next = build(tmp)
        payloads = make_payloads(req, args.clients, args.rows)

        results = []
        # max_batch=1 disables cross-request coalescing — the "batching
        # off" baseline SessionGroup docs compare against.
        configs = [
            ("single-nobatch", lambda: ModelServer(
                Predictor(model, tmp), max_batch=1, max_wait_ms=0.0)),
            ("single", lambda: ModelServer(
                Predictor(model, tmp), max_batch=256, max_wait_ms=1.0)),
        ] + [
            (f"group-{g}", (lambda g=g: ServerGroup(
                model, tmp, replicas=g, max_batch=256, max_wait_ms=1.0)))
            for g in groups
        ]
        if args.smoke:
            configs = [c for c in configs if c[0] != "single-nobatch"]
        for name, make in configs:
            server = make()
            server.warmup({k: np.asarray(v)[:args.rows]
                           for k, v in req.items()})
            http = HttpServer(server, port=0).start()
            try:
                # settle, then measure (stats cover the measured window only)
                drive(http.port, payloads, 0.5, 2)
                server.stats.reset()
                recs = drive(http.port, payloads, args.seconds, args.clients)
                out = summarize(name, recs, args.seconds, args.clients,
                                args.rows, server=server)
                results.append(out)
                print(json.dumps(out), flush=True)
                if args.smoke:
                    check_smoke_config(out, http)

                if groups and name == f"group-{max(groups)}":
                    phases = [(save_next, "+rolling-update"),
                              (lambda: save_next("delta"), "+delta-update"),
                              # second delta runs entirely on warm compile
                              # caches — the serving-cadence steady state
                              (lambda: save_next("delta"),
                               "+delta-update-warm")]
                    if args.smoke:
                        phases = phases[1:2]  # one delta update is enough
                    for fn, label in phases:
                        results.append(rolling_update_phase(
                            server, http, payloads, args, name, fn,
                            label=label))
            finally:
                http.stop()
                server.close()

        sections = {}
        if args.processes:
            sections["scale_out"] = scale_out_grid(args, results)
        if args.quantize:
            sections["quantized"] = quantize_arms(
                args, tmp, model, req, payloads, save_next, results)
        if args.grouped:
            sections["grouped"] = grouped_arms(args, results)
        if args.compute_reuse:
            sections["compute_reuse"] = compute_reuse_arms(args, results)
        sections["obs_overhead"] = obs_overhead_section(
            args, tmp, model, req, payloads)

        if args.smoke:
            check_smoke_results(results, groups)
            check_smoke_sections(sections)
            print("bench_serving smoke OK", flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"results": results, "protocol": vars(args),
                           **sections}, f, indent=1)
        return results


def check_smoke_config(out, http):
    """Structural asserts for one measured config: the stage breakdown is
    present and the SAME accounting is served live over /v1/stats."""
    for stage in ("queue", "pad", "device", "post", "e2e"):
        assert out["stages"][stage]["count"] > 0, (out["config"], stage)
    live = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{http.port}/v1/stats", timeout=10).read())
    assert set(live["stages"]) == set(out["stages"])
    assert live["model"]["version"] >= 0


def check_smoke_results(results, groups):
    by_name = {r["config"]: r for r in results}
    upd = by_name[f"group-{max(groups)}+delta-update"]
    assert upd["model_version_advanced"], upd
    assert upd["during_update_p99_ms"] is not None
    assert upd["model"]["updates"] >= 1


def check_smoke_sections(sections):
    """Structural asserts for the scale-out / quantized / grouped grids
    (timing-free — `roofline.py --assert-serving` owns the numeric
    gates): every requested arm ran, the CPU-split model exists, the
    quantized arm measured residency AND replayed deltas, the grouped
    arm measured candidates/sec both ways, and the socket tier rolled a
    delta update with zero failed requests (drive() raises otherwise)."""
    so = sections["scale_out"]
    assert so["arms"], so
    assert "1" in so["arms"] and len(so["arms"]) >= 2, so
    assert so["modeled"]["rps"], so
    qa = sections["quantized"]
    assert "int8" in qa, qa
    ri = qa["int8"]["residency"]
    assert ri["measured_bytes"] == ri["modeled_bytes"], ri
    assert "serving_compiles" in qa["int8"], qa
    gr = sections["grouped"]
    assert gr.get("grouped_cps") and gr.get("ungrouped_cps"), gr
    cr = sections["compute_reuse"]
    assert cr["arms"]["cache_off"]["rps"] and \
        cr["arms"]["cache_on"]["rps"], cr
    assert cr["bit_identical"] is True, cr
    assert cr["publish"]["invalidations"] >= 1, cr
    assert cr["publish"]["version_advanced"], cr
    assert "effective_qps_factor" in cr and "hit_rate" in cr, cr
    assert cr["zero_failed_requests"] is True, cr
    ob = sections["obs_overhead"]
    assert ob["arms"]["on"]["rps"] and ob["arms"]["off"]["rps"], ob
    me = ob["metrics_endpoint"]
    assert me["parsed"] and me["has_stage_histogram"] \
        and me["has_queue_depth"], me


def rolling_update_phase(server, http, payloads, args, name, save_next,
                         label="+rolling-update"):
    """Measure the rolling-update blip: a new checkpoint lands mid-load
    and poll_updates rolls it across replicas while clients keep
    hammering. Reports steady vs during-update latency and asserts the
    model version actually advanced with zero failed requests (drive()
    raises on any failure)."""
    v0 = server.predictor.model_info().get("step")
    server.stats.reset()
    window = {}
    done = threading.Event()

    def updater():
        try:
            time.sleep(args.seconds / 3)
            step = save_next()
            t0 = time.monotonic()
            changed = server.predictor.poll_updates()
            window.update(t0=t0, t1=time.monotonic(), changed=changed,
                          new_step=step)
        except Exception as e:  # surfaced below — fail loudly, not KeyError
            window["error"] = e
        finally:
            done.set()

    th = threading.Thread(target=updater)
    t_begin = time.monotonic()
    th.start()
    recs = drive(http.port, payloads, args.seconds, args.clients,
                 until_event=done)
    th.join()
    elapsed = time.monotonic() - t_begin
    if "error" in window:
        raise RuntimeError("rolling-update phase failed") from window["error"]

    t0, t1 = window["t0"] - 0.25, window["t1"] + 0.25
    # classify by interval OVERLAP: a request in flight when the update
    # starts belongs to the update window even if it started before it
    during = [dt for ts, dt in recs if ts <= t1 and ts + dt >= t0]
    steady = [dt for ts, dt in recs if ts + dt < t0 or ts > t1]
    v1 = server.predictor.model_info().get("step")
    out = summarize(
        name + label, recs, elapsed, args.clients,
        args.rows, server=server,
        extra={
            "steady_p99_ms": (
                round(1e3 * pct(steady, 0.99), 2) if steady else None),
            "during_update_p99_ms": (
                round(1e3 * pct(during, 0.99), 2) if during else None),
            "during_update_max_ms": (
                round(1e3 * max(during), 2) if during else None),
            "update_window_ms": round(1e3 * (window["t1"] - window["t0"]), 1),
            "model_version_advanced": bool(window["changed"]) and v1 != v0,
        },
    )
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    main()
