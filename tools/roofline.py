#!/usr/bin/env python
"""Roofline analysis of the DLRM training step: measured throughput vs the
hardware's memory-bandwidth and compute ceilings.

Methodology (docs/perf.md): count the step's algorithmic HBM traffic and
MXU FLOPs from the model config, run the step, and report how much of each
ceiling the measured examples/sec implies. The larger of the two fractions
identifies the binding roof; tuning stops being worth it as it approaches
1.0. Run on the target TPU:

    python tools/roofline.py [--batch 2048] [--emb_dim 16]
        [--peak_bw_gbs 1228] [--peak_tflops 275]   # v4 defaults

CPU runs exercise the accounting but say nothing about TPU roofs.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def mlp_flops(dims, batch):
    """2*in*out MACs->FLOPs per layer, forward only."""
    total = 0
    for a, b in zip(dims[:-1], dims[1:]):
        total += 2 * a * b * batch
    return total


def assert_traffic(json_path: str) -> int:
    """CI gate: the traffic model (deeprec_tpu/ops/traffic.py) must match
    the gather/scatter op counts bench.py measured off the actually-lowered
    lookup+apply program.  Drift — an op added to or removed from the hot
    path without the model learning about it — fails the smoke run."""
    import json

    with open(json_path) as f:
        rec = json.load(f)
    tr = rec.get("traffic")
    if not tr:
        print(f"roofline: {json_path} has no 'traffic' record", file=sys.stderr)
        return 1
    rc = 0
    for arm in ("diet", "legacy_apply"):
        # bench.py records both the measurement (op counts off the lowered
        # program) and the model's prediction from the same checkout; the
        # re-import here also catches a bench JSON produced by stale code.
        measured = tr["ops_measured"][arm]
        recorded = tr["ops_model"][arm]
        for kind in ("gather", "scatter"):
            if measured[kind] != recorded[kind]:
                print(
                    f"roofline: traffic-model drift [{arm}/{kind}]: "
                    f"measured {measured[kind]} vs model {recorded[kind]} "
                    f"— update deeprec_tpu/ops/traffic.py's op inventory "
                    f"to match the hot path",
                    file=sys.stderr,
                )
                rc = 1
    diet_s = tr["ops_measured"]["diet"]["scatter"]
    legacy_s = tr["ops_measured"]["legacy_apply"]["scatter"]
    if diet_s >= legacy_s:
        print(
            f"roofline: the diet no longer removes scatters "
            f"(diet {diet_s} vs legacy {legacy_s})", file=sys.stderr,
        )
        rc = 1
    if rc == 0:
        print(
            f"roofline: traffic model matches measurement "
            f"(diet {tr['ops_measured']['diet']}, legacy "
            f"{tr['ops_measured']['legacy_apply']}; diet removes "
            f"{legacy_s - diet_s} scatters)"
        )
    return rc


def assert_overlap(json_path: str, tol: float) -> int:
    """CI gate for the in-step pipelining grid (bench.py 'pipeline'
    section): the pipelined K-scan arms must exist, must not regress past
    `tol` relative to the sequential arm, the overlap model must be
    internally consistent (the overlapped schedule can never model SLOWER
    than the sequential sum), and the overlap efficiency
    (modeled max(exchange, dense) step vs the measured pipelined step)
    must be recorded. On CPU the efficiency is informational (no async
    collectives to realize the overlap); the regression bound is the
    enforced contract, and on TPU the printed efficiency is the number
    the ROADMAP item asks to close."""
    import json

    with open(json_path) as f:
        rec = json.load(f)
    pipe = rec.get("pipeline")
    if not pipe:
        print(f"roofline: {json_path} has no 'pipeline' record "
              "(run bench.py with --pipeline-mode grid)", file=sys.stderr)
        return 1
    modes = pipe.get("modes", {})
    if "off" not in modes or not any(m != "off" for m in modes):
        print("roofline: pipeline record needs an 'off' arm and at least "
              f"one pipelined arm, got {sorted(modes)}", file=sys.stderr)
        return 1
    rc = 0
    off_ms = modes["off"]["ms_per_step"]
    modeled = pipe.get("modeled_ms", {})
    eff = pipe.get("overlap_efficiency", {})
    for mode, stats in modes.items():
        if mode == "off":
            continue
        ms = stats["ms_per_step"]
        if ms > off_ms * (1.0 + tol):
            print(
                f"roofline: pipeline_mode={mode} REGRESSES the K-scan step "
                f"beyond tolerance: {ms:.3f} ms vs off {off_ms:.3f} ms "
                f"(bound {1.0 + tol:.2f}x) — the lookahead restructure "
                f"is costing more than the overlap hides",
                file=sys.stderr,
            )
            rc = 1
        if mode not in eff:
            print(f"roofline: pipeline arm {mode} missing its "
                  "overlap_efficiency entry", file=sys.stderr)
            rc = 1
        if mode in modeled and "off" in modeled and \
                modeled[mode] > modeled["off"] + 1e-9:
            print(
                f"roofline: overlap model inconsistent — modeled "
                f"{mode} {modeled[mode]} ms > modeled off "
                f"{modeled['off']} ms", file=sys.stderr,
            )
            rc = 1
    if rc == 0:
        arms = ", ".join(
            f"{m} {s['ms_per_step']:.2f}ms"
            f" (eff {eff.get(m, float('nan')):.2f},"
            f" modeled {modeled.get(m, '?')}ms)"
            for m, s in modes.items() if m != "off"
        )
        print(
            f"roofline: overlap gate ok — off {off_ms:.2f}ms vs {arms} "
            f"(phase_ms {pipe.get('phase_ms')})"
        )
    return rc


def assert_imbalance(json_path: str, factor: float, tol: float) -> int:
    """CI gate for the skew-aware placement arm (bench.py 'placement'
    section): on the skewed multi-table workload the adopted ShardPlan
    must cut the measured per-shard exchange-bytes imbalance (max/mean,
    ops/traffic.py shard_imbalance) by at least `factor` vs the uniform
    hash, with the plan arm's step time no worse than the uniform arm's
    beyond `tol` (re-routing hot keys and rotating owners must not buy
    balance with a slower step). The same counters back
    Trainer.dedup_stats()['per_shard'], so a violation here means live
    telemetry regressed too."""
    import json

    with open(json_path) as f:
        rec = json.load(f)
    pl = rec.get("placement")
    if not pl:
        print(f"roofline: {json_path} has no 'placement' record "
              "(run bench.py with --placement)", file=sys.stderr)
        return 1
    if pl.get("error"):
        print(f"roofline: placement arm failed: {pl['error']}",
              file=sys.stderr)
        return 1
    if "imbalance_after" not in pl:
        print("roofline: placement record has no plan arm "
              f"(mode={pl.get('mode')!r}) — run --placement grid",
              file=sys.stderr)
        return 1
    rc = 0
    before, after = pl["imbalance_before"], pl["imbalance_after"]
    if after * factor > before:
        print(
            f"roofline: placement gate FAILED — imbalance {before:.3f} -> "
            f"{after:.3f} is under the required {factor:.1f}x reduction "
            f"(the plan no longer flattens the skewed workload)",
            file=sys.stderr,
        )
        rc = 1
    ms = pl.get("step_ms", {})
    if "uniform" in ms and "plan" in ms and \
            ms["plan"] > ms["uniform"] * (1.0 + tol):
        print(
            f"roofline: placement gate FAILED — plan step "
            f"{ms['plan']:.3f} ms vs uniform {ms['uniform']:.3f} ms "
            f"(bound {1.0 + tol:.2f}x): the routing table / migration "
            f"overhead outweighs the balance win",
            file=sys.stderr,
        )
        rc = 1
    rc |= _assert_drift(pl.get("drift"))
    if rc == 0:
        print(
            f"roofline: placement gate ok — imbalance {before:.3f} -> "
            f"{after:.3f} ({before / max(after, 1e-9):.2f}x, bound "
            f"{factor:.1f}x), step {ms.get('uniform')} -> {ms.get('plan')}"
            f" ms, moved {pl.get('moved_rows')} rows, "
            f"{pl.get('hot_keys')} hot keys"
        )
    return rc


def _assert_drift(drift, peak_floor: float = 2.0,
                  recover_bound: float = 1.3) -> int:
    """Drifting-skew replanning gates (bench.py placement 'drift' arm,
    round 19): after the hot set rotates mid-stream the stale plan's
    measured imbalance must spike past `peak_floor`, an AUTOMATIC
    (drift-triggered, amortization-approved, never forced) replan must
    fire, and the trajectory must recover to <= `recover_bound` — with
    ZERO a2a overflow across the whole run (the per-dest budget's
    drift-safety margin covers the stale window) and the per-dest-budget
    wire model strictly below the v1 global-headroom model with the
    compiled buckets matching the budget vector exactly."""
    if not drift:
        print("roofline: placement record has no 'drift' arm — run "
              "bench.py --placement grid", file=sys.stderr)
        return 1
    rc = 0
    reps = drift.get("replans", {})
    if reps.get("post_drift_auto", 0) < 1:
        print("roofline: drift gate FAILED — no automatic post-drift "
              f"replan fired (replans: {reps})", file=sys.stderr)
        rc = 1
    if reps.get("forced", 0):
        print("roofline: drift gate FAILED — replans were forced "
              f"({reps}); the trigger path was not exercised",
              file=sys.stderr)
        rc = 1
    peak = drift.get("peak_post_drift") or 0.0
    if peak < peak_floor:
        print(
            f"roofline: drift gate FAILED — post-drift imbalance peaked "
            f"at {peak:.3f} < {peak_floor:.1f}: the rotation no longer "
            f"stresses the stale plan (workload drifted?)",
            file=sys.stderr)
        rc = 1
    rec = drift.get("recovered_imbalance")
    if rec is None or rec > recover_bound:
        print(
            f"roofline: drift gate FAILED — imbalance recovered to "
            f"{rec} > {recover_bound} after the replan(s): the replanner "
            f"no longer flattens the rotated hot set", file=sys.stderr)
        rc = 1
    if drift.get("a2a_overflow", 1) != 0:
        print(
            f"roofline: drift gate FAILED — {drift.get('a2a_overflow')} "
            f"a2a overflow(s): the per-dest budget degraded rows "
            f"(default-served) somewhere in the drift window",
            file=sys.stderr)
        rc = 1
    if not drift.get("budgets_measured_eq_modeled"):
        print(
            "roofline: drift gate FAILED — a compiled a2a bucket "
            "diverged from the modeled per-dest budget vector "
            f"(budgets: {drift.get('budgets')})", file=sys.stderr)
        rc = 1
    wp = drift.get("wire_bytes_per_dest_model")
    wg = drift.get("wire_bytes_global_headroom_model")
    if wp is None or wg is None or not wp < wg:
        print(
            f"roofline: drift gate FAILED — per-dest-budget wire bytes "
            f"{wp} not strictly below the global-headroom model {wg}",
            file=sys.stderr)
        rc = 1
    if rc == 0:
        print(
            f"roofline: drift gate ok — peak {peak:.3f} -> recovered "
            f"{rec:.3f} (bound {recover_bound}), "
            f"{reps.get('post_drift_auto')} automatic post-drift "
            f"replan(s), 0 overflow, wire {wp:.0f} < global {wg:.0f} "
            f"({wg / max(wp, 1e-9):.2f}x diet)"
        )
    return rc


def assert_compiles(json_path: str, budget: int) -> int:
    """CI gate for the steady-state retrace contract (bench.py
    'trace_guard' section, analysis/trace_guard.py): after each arm's
    warmup window, the timed measurement loops must compile ZERO new XLA
    programs. A nonzero count means something inside the measured step
    re-traces per call (a fresh jit wrapper, an unstable cache key, an
    unwarmed shape) — the DRT001/PR 5 class — and every throughput
    number in the file was measured through compile stalls."""
    import json

    with open(json_path) as f:
        rec = json.load(f)
    tg = rec.get("trace_guard")
    if not tg:
        print(f"roofline: {json_path} has no 'trace_guard' record "
              "(bench.py too old?)", file=sys.stderr)
        return 1
    total = tg.get("steady_state_compiles")
    if total is None:
        print("roofline: trace_guard record has no steady_state_compiles",
              file=sys.stderr)
        return 1
    if total > budget:
        bad = {a: n for a, n in tg.get("per_arm", {}).items() if n}
        print(
            f"roofline: steady-state compile gate FAILED — {total} XLA "
            f"compile(s) inside timed windows (budget {budget}): {bad} — "
            "something in the measured step retraces per call; run the "
            "static analyzer (python -m deeprec_tpu.analysis --check) "
            "and check for fresh jit wrappers on the hot path",
            file=sys.stderr,
        )
        return 1
    print(
        f"roofline: steady-state compile gate ok — 0 compiles across "
        f"{len(tg.get('per_arm', {}))} timed arm(s) "
        f"(budget {budget})"
    )
    return 0


def assert_hierarchy(json_path: str, inter_ratio: float, tol: float) -> int:
    """CI gate for the pod-scale 2-D mesh arm (bench.py 'mesh' section,
    round 19): the hierarchical two-tier exchange must actually put the
    expensive tier on a diet, exactly, and for free.

    Checks: (1) the modeled inter-tier wire bytes at the reference 2x4
    shape sit at <= `inter_ratio` x the flat a2a's inter-host bytes AND
    <= 1/intra of the flat a2a's TOTAL bytes (the hierarchy must beat
    both the same-tier column and the naive per-link share); (2) the
    compiled inter bucket equals the model's budget max per bundle
    (model and program share `ops/traffic.py hier_dest_budgets` — drift
    means one changed without the other); (3) ZERO budget overflow
    (group aggregation stayed inside U_g = group_factor x U); (4) ZERO
    steady-state compiles across every arm's timed windows (the nested
    pipeline restructure must not retrace); (5) BITWISE first-step loss
    parity across flat 1-D, hier, and nested arms (the forward under the
    hierarchy is exact — one contributor per psum_scatter position);
    (6) the nested K-scan within `tol` of the unpipelined hier K-scan
    (same discipline as --assert-overlap: on CPU the restructure cost is
    the enforced bound, the overlap win is the TPU number)."""
    import json

    with open(json_path) as f:
        rec = json.load(f)
    mesh = rec.get("mesh")
    if not mesh:
        print(f"roofline: {json_path} has no 'mesh' record "
              "(run bench.py with --mesh)", file=sys.stderr)
        return 1
    if mesh.get("error"):
        print(f"roofline: mesh arm failed: {mesh['error']}", file=sys.stderr)
        return 1
    arms = mesh.get("arms", {})
    hier = mesh.get("hier")
    need = {"1d_a2a", "2d_hier", "2d_nested"}
    if not need <= set(arms) or not hier:
        print(f"roofline: mesh record needs arms {sorted(need)} + the "
              f"'hier' tier model (mode={mesh.get('mode')!r}) — run "
              "--mesh grid", file=sys.stderr)
        return 1
    rc = 0
    r_inter = hier.get("inter_ratio_vs_flat_inter")
    if r_inter is None or r_inter > inter_ratio:
        print(
            f"roofline: hierarchy gate FAILED — modeled inter-tier bytes "
            f"are {r_inter}x the flat a2a's inter-host bytes (bound "
            f"{inter_ratio}): the two-tier exchange no longer diets the "
            f"expensive tier", file=sys.stderr)
        rc = 1
    r_total = hier.get("inter_ratio_vs_flat_total_over_intra")
    if r_total is None or r_total > 1.0:
        print(
            f"roofline: hierarchy gate FAILED — modeled inter-tier bytes "
            f"are {r_total}x the flat total/intra share (bound 1.0): the "
            f"hierarchy moves MORE across the expensive tier than each "
            f"flat link's naive share", file=sys.stderr)
        rc = 1
    if not hier.get("buckets_measured_eq_modeled"):
        print(
            "roofline: hierarchy gate FAILED — a compiled inter bucket "
            "diverged from the modeled hier_dest_budgets max "
            f"(per_bundle: {hier.get('per_bundle')})", file=sys.stderr)
        rc = 1
    if mesh.get("overflow", 1) != 0:
        print(
            f"roofline: hierarchy gate FAILED — {mesh.get('overflow')} "
            "budget overflow(s): the group unique budget U_g degraded "
            "rows (default-served) on this stream", file=sys.stderr)
        rc = 1
    compiles = mesh.get("trace_guard", {}).get("steady_state_compiles")
    if compiles != 0:
        print(
            f"roofline: hierarchy gate FAILED — {compiles} steady-state "
            "XLA compile(s) inside timed windows (contract 0; per arm: "
            f"{ {a: s.get('steady_compiles') for a, s in arms.items()} })",
            file=sys.stderr)
        rc = 1
    if not mesh.get("first_loss_equal"):
        print(
            "roofline: hierarchy gate FAILED — first-step loss diverged "
            "across arms (forward must be BITWISE identical): "
            f"{ {a: s.get('first_loss') for a, s in arms.items()} }",
            file=sys.stderr)
        rc = 1
    off_ms = arms["2d_hier"]["scan_ms_per_step"]
    nested_ms = arms["2d_nested"]["scan_ms_per_step"]
    if nested_ms > off_ms * (1.0 + tol):
        print(
            f"roofline: hierarchy gate FAILED — nested K-scan "
            f"{nested_ms:.3f} ms vs unpipelined hier {off_ms:.3f} ms "
            f"(bound {1.0 + tol:.2f}x): the two-tier lookahead "
            "restructure costs more than tolerance", file=sys.stderr)
        rc = 1
    if rc == 0:
        mb = hier.get("modeled_bytes", {})
        print(
            f"roofline: hierarchy gate ok — inter tier "
            f"{mb.get('hier_inter')}B = {r_inter}x flat inter-host "
            f"(bound {inter_ratio}), {r_total}x flat total/intra, "
            f"0 overflow, 0 steady compiles, bitwise loss parity, "
            f"nested scan {nested_ms:.2f}ms vs {off_ms:.2f}ms "
            f"(bound {1.0 + tol:.2f}x)"
        )
    return rc


def assert_serving(json_path: str, scale_floor: float,
                   grouped_factor: float, quant_ratio: float) -> int:
    """CI gate for the serving scale-out grid (tools/bench_serving.py
    --processes/--quantize/--grouped JSON):

      * scaling — at the largest process count P the tier must reach
        `scale_floor`·P speedup over one process. On a host with enough
        cores the MEASURED speedup is gated; on a core-starved host
        (`cpu_limited`, e.g. single-core CI where N processes time-slice
        one core) the CPU-split Amdahl model carries the claim — same
        discipline as --assert-overlap, where single-core CI gates the
        contract and the capable host pins the measurement.
      * quantized residency — measured bytes must equal the
        ops/traffic.py model EXACTLY (the accounting is shape math, not
        an estimate), int8 must sit under `quant_ratio`× the fp32
        baseline, and the delta replay under the trace guard must have
        compiled ZERO programs (the zero-retrace serving contract on the
        quantized import path).
      * grouped — the two-tower arm's candidates/sec with sample-aware
        user-tower reuse must beat the plain arm by `grouped_factor`×.
    """
    import json

    with open(json_path) as f:
        rec = json.load(f)
    rc = 0

    so = rec.get("scale_out")
    if not so or not so.get("arms"):
        print(f"roofline: {json_path} has no 'scale_out' record "
              "(run bench_serving with --processes)", file=sys.stderr)
        rc = 1
    else:
        counts = sorted(int(k) for k in so["arms"])
        P = counts[-1]
        need = scale_floor * P
        measured = so.get("measured_speedup", {}).get(str(P))
        if so.get("cpu_limited"):
            sp = so.get("modeled", {}).get("speedup", {}).get(str(P))
            kind = f"modeled (host has {so.get('host_cores')} core(s) for " \
                   f"{P} backends + the edge: measured arms are core-bound)"
        else:
            sp = measured
            kind = "measured"
        if sp is None or sp < need:
            print(
                f"roofline: serving scale-out gate FAILED — {kind} speedup "
                f"at {P} processes is {sp} (need ≥ {need:.2f} = "
                f"{scale_floor:.2f}×{P}); measured {measured}",
                file=sys.stderr,
            )
            rc = 1
        else:
            print(
                f"roofline: serving scale-out ok — {kind} speedup {sp:.2f} "
                f"at {P} processes (floor {need:.2f}; measured "
                f"{measured}, cpu split {so.get('modeled', {}).get('frontend_cpu_per_req_ms')}"
                f"/{so.get('modeled', {}).get('backend_cpu_per_req_ms')} ms "
                f"front/back per request)"
            )

    qa = rec.get("quantized", {})
    q8 = qa.get("int8")
    if not q8:
        print(f"roofline: {json_path} has no int8 'quantized' record "
              "(run bench_serving with --quantize int8)", file=sys.stderr)
        rc = 1
    else:
        ri = q8["residency"]
        if ri["measured_bytes"] != ri["modeled_bytes"]:
            print(
                f"roofline: quantized residency gate FAILED — measured "
                f"{ri['measured_bytes']}B != modeled {ri['modeled_bytes']}B "
                f"(ops/traffic.py serving_residency_bytes drifted from the "
                f"actual table layout)", file=sys.stderr,
            )
            rc = 1
        if ri["measured_bytes"] > quant_ratio * ri["fp32_bytes"]:
            print(
                f"roofline: quantized residency gate FAILED — int8 bytes "
                f"{ri['measured_bytes']} exceed {quant_ratio:.2f}× the fp32 "
                f"baseline {ri['fp32_bytes']}", file=sys.stderr,
            )
            rc = 1
        if q8.get("serving_compiles", -1) != 0:
            print(
                f"roofline: quantized serving compile gate FAILED — "
                f"{q8.get('serving_compiles')} XLA compile(s) during the "
                f"guarded delta replay (the quantize-on-import path "
                f"retraces; must be 0)", file=sys.stderr,
            )
            rc = 1
        if rc == 0:
            print(
                f"roofline: quantized residency ok — int8 "
                f"{ri['measured_bytes'] / 2 ** 20:.2f} MiB = "
                f"{ri['measured_bytes'] / ri['fp32_bytes']:.3f}× fp32 "
                f"(bound {quant_ratio:.2f}), model exact, 0 replay compiles"
            )

    gr = rec.get("grouped")
    if not gr or not gr.get("factor"):
        print(f"roofline: {json_path} has no 'grouped' record "
              "(run bench_serving with --grouped)", file=sys.stderr)
        rc = 1
    elif gr["factor"] < grouped_factor:
        print(
            f"roofline: grouped serving gate FAILED — candidates/sec "
            f"factor {gr['factor']} under the {grouped_factor:.1f}× floor "
            f"(grouped {gr.get('grouped_cps')} vs ungrouped "
            f"{gr.get('ungrouped_cps')} at {gr.get('rows_per_request')} "
            f"candidates/request)", file=sys.stderr,
        )
        rc = 1
    else:
        print(
            f"roofline: grouped serving ok — {gr['factor']:.2f}× "
            f"candidates/sec ({gr.get('grouped_cps')} vs "
            f"{gr.get('ungrouped_cps')} at {gr.get('rows_per_request')} "
            f"candidates/request)"
        )

    rc |= _assert_multi_host(rec.get("multi_host"), json_path)
    return rc


def _assert_multi_host(mh, json_path: str) -> int:
    """The fleet gate (tools/bench_fleet.py `multi_host` section):
    sustained rps through a rolling restart of EVERY backend and a
    scale-out/-in event (2→4→2; the smoke tier runs the same walk) with
    ZERO failed requests anywhere — the ROADMAP's multi-host headline.
    Structural honesty only: rps floors belong to capable hosts, the
    zero-failure and coverage contracts hold on any host."""
    if not mh:
        print(f"roofline: {json_path} has no 'multi_host' record "
              "(run tools/bench_fleet.py --out onto this JSON)",
              file=sys.stderr)
        return 1
    rc = 0
    phases = {"steady": mh.get("steady", {}),
              "rolling_restart": mh.get("rolling_restart", {}),
              "scale": mh.get("scale", {}),
              **{f"faults.{k}": v
                 for k, v in mh.get("faults", {}).items()}}
    for name, ph in phases.items():
        if ph.get("failed_requests", 1) != 0:
            print(f"roofline: fleet gate FAILED — phase {name} recorded "
                  f"{ph.get('failed_requests')} failed request(s); the "
                  f"fleet contract is ZERO through every churn event",
                  file=sys.stderr)
            rc = 1
        if name in ("steady", "rolling_restart", "scale") and \
                not ph.get("rps"):
            print(f"roofline: fleet gate FAILED — phase {name} sustained "
                  f"no traffic (rps {ph.get('rps')})", file=sys.stderr)
            rc = 1
    roll = phases["rolling_restart"]
    if not roll.get("covered_all") or roll.get("restarted", 0) < 2:
        print(f"roofline: fleet gate FAILED — rolling restart covered "
              f"{roll.get('restarted')}/{roll.get('fleet_size')} backends "
              f"(must roll EVERY member)", file=sys.stderr)
        rc = 1
    if roll.get("unplanned_restarts", 0) != 0:
        print(f"roofline: fleet gate FAILED — "
              f"{roll.get('unplanned_restarts')} UNPLANNED supervisor "
              f"restart(s) during the roll (drain must exit via "
              f"EXIT_RESCALE, not crash)", file=sys.stderr)
        rc = 1
    sc = phases["scale"]
    path = sc.get("path") or []
    tmax = sc.get("target_max", 4)
    if (len(path) < 3 or path[0] != path[-1] or max(path) != tmax
            or max(path) - path[0] < 2):
        print(f"roofline: fleet gate FAILED — scale path {path} is not a "
              f"{path[0] if path else '?'}→{tmax}→"
              f"{path[0] if path else '?'} round trip", file=sys.stderr)
        rc = 1
    if not mh.get("zero_failed_requests"):
        print("roofline: fleet gate FAILED — zero_failed_requests is "
              "false", file=sys.stderr)
        rc = 1
    if rc == 0:
        print(
            f"roofline: fleet ok — rolled {roll.get('restarted')}/"
            f"{roll.get('fleet_size')} backends at "
            f"{roll.get('rps')} rps (p99 {roll.get('p99_ms')} ms), "
            f"scale {'→'.join(str(x) for x in path)} at "
            f"{sc.get('rps')} rps, {mh.get('total_requests')} requests, "
            f"0 failed"
        )
    return rc


def assert_retrieval(json_path: str, recall_floor: float,
                     sweep_factor: float, freshness_factor: float) -> int:
    """CI gate for full-corpus retrieval (tools/bench_retrieval.py
    'retrieval' section):

      * recall — the int8 blocked sweep must hold `recall_floor` at
        recall@100 against the exact fp32 full-scan argsort (tie-aware:
        identical-vector items are interchangeable answers).
      * sweep vs gather — the resident blocked sweep must beat the
        per-row gather-and-re-encode baseline by `sweep_factor`× at the
        1M-item smoke shape (the reason the corpus matrix exists).
      * freshness — ingest -> retrievable (trainer commit to the corpus
        fold that covers the delta) must sit within `freshness_factor`×
        the predictor's own pinned train_to_serve lag: retrieval
        freshness rides the SAME poll round as serving freshness, so a
        big gap means the fold left the round.
      * residency — measured sweep bytes must equal the
        `ops/traffic.py retrieval_sweep_bytes` model EXACTLY (shape
        math, not an estimate), and the int8 corpus must sit strictly
        under the fp32 arm's bytes.
      * compiles — delta replay folding into the corpus matrix must
        compile ZERO steady-state XLA programs (the PR 5 zero-retrace
        serving contract extended to the retrieval lane).
    """
    import json

    with open(json_path) as f:
        rec = json.load(f)
    rt = rec.get("retrieval")
    if not rt:
        print(f"roofline: {json_path} has no 'retrieval' record "
              "(run tools/bench_retrieval.py --out onto this JSON)",
              file=sys.stderr)
        return 1
    rc = 0
    rec100 = (rt.get("recall", {}).get("int8", {}) or {}).get(
        "recall_at_100")
    if rec100 is None or rec100 < recall_floor:
        print(f"roofline: retrieval gate FAILED — int8 recall@100 "
              f"{rec100} under the {recall_floor:.2f} floor vs exact "
              f"fp32 scan (quantized blocked sweep lost ranking "
              f"fidelity)", file=sys.stderr)
        rc = 1
    sg = rt.get("sweep_vs_gather") or {}
    if not sg.get("speedup") or sg["speedup"] < sweep_factor:
        print(f"roofline: retrieval gate FAILED — blocked sweep speedup "
              f"{sg.get('speedup')} under the {sweep_factor:.1f}× floor "
              f"vs the per-row gather baseline at "
              f"{sg.get('corpus_rows')} items", file=sys.stderr)
        rc = 1
    fr = rt.get("freshness") or {}
    retr = fr.get("retrievable_seconds")
    pinned = fr.get("pinned_lag_seconds")
    if retr is None or pinned is None or \
            retr > freshness_factor * max(pinned, 0.05):
        print(f"roofline: retrieval gate FAILED — ingest->retrievable "
              f"{retr}s exceeds {freshness_factor:.1f}× the pinned "
              f"train_to_serve lag {pinned}s (the corpus fold left the "
              f"poll round)", file=sys.stderr)
        rc = 1
    if fr.get("rows_folded", 0) < 1:
        print("roofline: retrieval gate FAILED — the freshness delta "
              "folded zero corpus rows (changed-key discovery broke)",
              file=sys.stderr)
        rc = 1
    resd = rt.get("residency") or {}
    q8, q32 = resd.get("int8"), resd.get("fp32")
    if not q8 or not q32:
        print("roofline: retrieval gate FAILED — residency arms missing "
              "(need int8 AND fp32)", file=sys.stderr)
        rc = 1
    else:
        for name, ri in (("int8", q8), ("fp32", q32)):
            if ri["measured_bytes"] != ri["modeled_bytes"]:
                print(f"roofline: retrieval gate FAILED — {name} sweep "
                      f"bytes measured {ri['measured_bytes']} != modeled "
                      f"{ri['modeled_bytes']} (retrieval_sweep_bytes "
                      f"drifted from the corpus layout)", file=sys.stderr)
                rc = 1
        if q8["measured_bytes"] >= q32["measured_bytes"]:
            print(f"roofline: retrieval gate FAILED — int8 corpus "
                  f"{q8['measured_bytes']}B not under fp32 "
                  f"{q32['measured_bytes']}B", file=sys.stderr)
            rc = 1
    if rt.get("steady_compiles", -1) != 0:
        print(f"roofline: retrieval gate FAILED — "
              f"{rt.get('steady_compiles')} XLA compile(s) during the "
              f"guarded delta-replay fold + retrieve (must be 0)",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        arms = {n: a.get("int8", {}).get("qps")
                for n, a in (rt.get("arms") or {}).items()}
        print(f"roofline: retrieval gate ok — recall@100 {rec100} "
              f"(floor {recall_floor}), sweep {sg['speedup']}× gather "
              f"at {sg.get('corpus_rows')} items, freshness {retr}s ≤ "
              f"{freshness_factor:.0f}×{pinned}s, int8 corpus "
              f"{q8['measured_bytes'] / 2 ** 20:.1f} MiB = "
              f"{q8['measured_bytes'] / q32['measured_bytes']:.3f}× "
              f"fp32 (model exact), 0 fold compiles, qps {arms}")
    return rc


def assert_reuse(json_path: str, qps_factor: float,
                 hit_floor: float) -> int:
    """CI gate for the frontend compute-reuse layer (tools/bench_serving.py
    --compute-reuse JSON, serving/reuse.py):

      * effective qps — the zipf arm with the version-keyed answer cache
        ON must reach `qps_factor`× the cache-off arm's measured qps on
        the SAME request stream (the ROADMAP's ≥2× headline; the
        ops/traffic.py serving_reuse_speedup model is the recorded
        zero-hit-cost ceiling).
      * hit rate — the steady window must hold `hit_floor` (the zipf
        head is resident; below this the population/capacity drifted and
        the qps factor is measuring noise).
      * correctness — the miss/hit/`no_cache` probe must be
        byte-identical at one version (the cache is a pure memo), and a
        steady-window cache hit must compile ZERO XLA programs.
      * version boundary — the mid-load delta publish must show the
        invalidation dip (dip < pre) AND recovery (recovered > dip) with
        ≥1 invalidation and the version advanced: entries die exactly at
        the swap, never by sweep, and never serve across it.
      * memory — recorded occupancy must sit within the byte capacity.
    """
    import json

    with open(json_path) as f:
        rec = json.load(f)
    cr = rec.get("compute_reuse")
    if not cr:
        print(f"roofline: {json_path} has no 'compute_reuse' record "
              "(run bench_serving with --compute-reuse)", file=sys.stderr)
        return 1
    rc = 0
    arms = cr.get("arms", {})
    if "cache_on" not in arms or "cache_off" not in arms:
        print("roofline: compute_reuse needs cache_on and cache_off arms, "
              f"got {sorted(arms)}", file=sys.stderr)
        return 1
    factor = cr.get("effective_qps_factor")
    if factor is None or factor < qps_factor:
        print(
            f"roofline: reuse gate FAILED — effective qps factor {factor} "
            f"under the {qps_factor:.1f}× floor (cache on "
            f"{arms['cache_on'].get('rps')} vs off "
            f"{arms['cache_off'].get('rps')} rps at hit rate "
            f"{cr.get('hit_rate')}; modeled ceiling "
            f"{cr.get('modeled', {}).get('speedup_ceiling_at_hit_rate')})",
            file=sys.stderr,
        )
        rc = 1
    hr = cr.get("hit_rate")
    if hr is None or hr < hit_floor:
        print(
            f"roofline: reuse gate FAILED — steady hit rate {hr} under "
            f"the {hit_floor:.2f} floor (zipf α={cr.get('zipf_alpha')}, "
            f"{cr.get('users')} users): the resident head no longer "
            f"covers the stream", file=sys.stderr,
        )
        rc = 1
    if cr.get("bit_identical") is not True:
        print(
            "roofline: reuse gate FAILED — miss/hit/no_cache probe was "
            "not byte-identical: the cache is serving answers a fresh "
            "eval would not produce", file=sys.stderr,
        )
        rc = 1
    if cr.get("steady_compiles", -1) != 0:
        print(
            f"roofline: reuse gate FAILED — {cr.get('steady_compiles')} "
            "XLA compile(s) inside the guarded cache-on steady window "
            "(a cache hit must never trace; must be 0)", file=sys.stderr,
        )
        rc = 1
    pub = cr.get("publish") or {}
    pre, dip, recov = (pub.get("pre_hit_rate"), pub.get("dip_hit_rate"),
                       pub.get("recovered_hit_rate"))
    if pre is None or dip is None or recov is None or \
            not (dip < pre and recov > dip):
        print(
            f"roofline: reuse gate FAILED — publish window did not show "
            f"the invalidation dip + recovery (pre {pre} → dip {dip} → "
            f"recovered {recov}): the version swap is not the "
            f"invalidation edge", file=sys.stderr,
        )
        rc = 1
    if pub.get("invalidations", 0) < 1 or not pub.get("version_advanced"):
        print(
            f"roofline: reuse gate FAILED — the mid-load delta publish "
            f"invalidated {pub.get('invalidations')} entries with "
            f"version_advanced={pub.get('version_advanced')} (the swap "
            f"must drop every old-version entry)", file=sys.stderr,
        )
        rc = 1
    if not cr.get("occupancy_within_capacity"):
        print(
            f"roofline: reuse gate FAILED — cache occupancy "
            f"{arms.get('cache_on', {}).get('occupancy_bytes')}B exceeds "
            f"the {cr.get('capacity_bytes')}B budget (the byte bound is "
            f"the memory contract)", file=sys.stderr,
        )
        rc = 1
    if rc == 0:
        print(
            f"roofline: reuse gate ok — {factor:.2f}× effective qps "
            f"(floor {qps_factor:.1f}×; on {arms['cache_on'].get('rps')} "
            f"vs off {arms['cache_off'].get('rps')} rps), hit rate "
            f"{hr:.3f} (floor {hit_floor:.2f}), bit-identical probe, "
            f"0 steady compiles, publish dip {pre:.3f}→{dip:.3f}→"
            f"{recov:.3f} with {pub.get('invalidations')} "
            f"invalidation(s), occupancy "
            f"{arms['cache_on'].get('occupancy_bytes')}B ≤ "
            f"{cr.get('capacity_bytes')}B"
        )
    return rc


def assert_fused(json_path: str, ratio_bound: float) -> int:
    """CI gate for the fused sparse step (tools/bench_lookup.py
    --fused-step JSON, ops/fused_lookup.fused_sparse_*):

      * HBM diet — modeled fused-path bytes ≤ `ratio_bound`× the
        split-phase path at the recorded bench shapes. Both arms are
        RECOMPUTED here from the recorded shape params through
        ops/traffic.fused_sparse_step_traffic and must equal the recorded
        numbers — so neither the bench nor the model can drift away from
        the other and silently keep passing.
      * parity — the interpret-mode oracle probe (forward bitwise,
        backward bitwise at fp32, seeded-SR bitwise at bf16, both sides
        jitted) must have passed when the record was made.
    """
    import json

    from deeprec_tpu.ops.traffic import fused_sparse_step_traffic

    with open(json_path) as f:
        rec = json.load(f)
    fs = rec.get("fused_step")
    if not fs:
        print(f"roofline: {json_path} has no 'fused_step' record "
              "(run bench_lookup with --fused-step --out)", file=sys.stderr)
        return 1
    rc = 0
    sh, modeled = fs.get("shapes", {}), fs.get("modeled", {})
    try:
        model = {
            fused: fused_sparse_step_traffic(
                positions=sh["positions"], batch=sh["batch"],
                unique=sh["unique"], dim=sh["dim"],
                value_bytes={"float32": 4, "bfloat16": 2}[sh["dtype"]],
                slot_widths=tuple(sh["slot_widths"]), fused=fused,
            )["hbm_bytes"]
            for fused in (False, True)
        }
    except KeyError as e:
        print(f"roofline: fused_step record is missing shape param {e} — "
              "regenerate with the current bench_lookup", file=sys.stderr)
        return 1
    for arm, fused in (("unfused", False), ("fused", True)):
        got = modeled.get(f"{arm}_hbm_bytes")
        if got != model[fused]:
            print(
                f"roofline: fused gate FAILED — recorded {arm} model "
                f"{got} B != recomputed {model[fused]} B at the recorded "
                "shapes: bench and traffic model drifted apart",
                file=sys.stderr,
            )
            rc = 1
    ratio = model[True] / model[False]
    if ratio > ratio_bound:
        print(
            f"roofline: fused gate FAILED — modeled fused HBM "
            f"{ratio:.3f}× unfused exceeds the {ratio_bound:.2f}× bound "
            f"(fused {model[True] / 1e3:.1f} vs unfused "
            f"{model[False] / 1e3:.1f} KB/step at U={sh.get('unique')} "
            f"N={sh.get('positions')} D={sh.get('dim')})", file=sys.stderr,
        )
        rc = 1
    parity = fs.get("parity", {})
    bad = [k for k in ("forward_bitwise", "backward_bitwise",
                       "bf16_sr_bitwise") if parity.get(k) is not True]
    if bad:
        print(
            f"roofline: fused gate FAILED — oracle parity flags {bad} "
            f"not true in the record (backend {fs.get('backend')}): the "
            "fused kernels no longer match the split-phase path",
            file=sys.stderr,
        )
        rc = 1
    if rc == 0:
        print(
            f"roofline: fused gate ok — modeled fused HBM {ratio:.3f}× "
            f"unfused (bound {ratio_bound:.2f}×; fused "
            f"{model[True] / 1e3:.1f} vs unfused {model[False] / 1e3:.1f} "
            f"KB/step/table at the bench shapes), parity "
            f"fwd/bwd/bf16-SR all bitwise on {fs.get('backend')}"
        )
    return rc


def assert_obs(json_path: str, tol: float) -> int:
    """CI gate for the telemetry plane (bench.py / tools/bench_serving.py
    'obs_overhead' section): both arms (instrumented vs DEEPREC_OBS=off)
    must exist, the gated overhead — per-record registry cost × obs ops
    per step/request over the measured step/request time, a deterministic
    model (same discipline as the CPU-limited serving gate: wall-clock
    arm deltas on a shared CI box are noise beyond any honest overhead
    bound; the raw arms are recorded for inspection) — must sit under
    `tol`, and the recorded /metrics (or registry-render) parse check
    must have passed with a nonzero series count. Instrumentation whose
    cost grows past 2% of the hot path is a regression this fails."""
    import json

    with open(json_path) as f:
        rec = json.load(f)
    ob = rec.get("obs_overhead")
    if not ob:
        print(f"roofline: {json_path} has no 'obs_overhead' record "
              "(bench too old?)", file=sys.stderr)
        return 1
    rc = 0
    arms = ob.get("arms", {})
    if "on" not in arms or "off" not in arms:
        print("roofline: obs_overhead needs 'on' and 'off' arms, got "
              f"{sorted(arms)}", file=sys.stderr)
        rc = 1
    ov = ob.get("overhead_pct")
    if ov is None or ov > tol * 100.0:
        print(
            f"roofline: obs overhead gate FAILED — modeled overhead "
            f"{ov}% exceeds {tol * 100:.1f}% "
            f"(per_record_ns {ob.get('per_record_ns')}, ops "
            f"{ob.get('ops_per_step', ob.get('ops_per_request'))}) — the "
            "metrics plane got too expensive for the hot path",
            file=sys.stderr,
        )
        rc = 1
    me = ob.get("metrics_endpoint") or ob.get("metrics_parse")
    if not me or not me.get("parsed") or not me.get("series"):
        print(
            f"roofline: obs exposition gate FAILED — /metrics parse check "
            f"missing or failed ({me}) — the Prometheus rendering broke",
            file=sys.stderr,
        )
        rc = 1
    if rc == 0:
        print(
            f"roofline: obs gate ok — modeled overhead {ov}% "
            f"(bound {tol * 100:.1f}%; measured arms on/off "
            f"{arms['on']} / {arms['off']}), "
            f"{me['series']} metric series parsed"
        )
    return rc


def assert_guard(json_path: str, detect_budget: int,
                 recovery_ms: float) -> int:
    """CI gate for the model-quality firewall (tools/bench_guard.py
    'guard' section): under the injected poison matrix (NaN features,
    extreme magnitudes, label flips, stream-replayed repeats, an
    exploding-LR window) the served model's AUC must never cross the
    recorded floor, ZERO requests may fail, every poison delivery must
    be detected within `detect_budget` dispatches, the replayed batch
    must end permanently quarantined, the pre-swap canary must have
    rejected the out-of-band poisoned delta (health degraded:
    quality_gate), and the last rollback+replay must complete within
    `recovery_ms`."""
    import json

    with open(json_path) as f:
        rec = json.load(f)
    g = rec.get("guard")
    if not g:
        print(f"roofline: {json_path} has no 'guard' record "
              "(run tools/bench_guard.py --out onto this JSON)",
              file=sys.stderr)
        return 1
    rc = 0
    if g.get("failed_requests", 1) != 0:
        print(f"roofline: guard gate FAILED — {g.get('failed_requests')} "
              f"failed request(s) under poison "
              f"({g.get('request_errors')}); the firewall contract is "
              f"ZERO", file=sys.stderr)
        rc = 1
    events = g.get("events") or []
    if not events:
        print("roofline: guard gate FAILED — no poison deliveries "
              "recorded", file=sys.stderr)
        rc = 1
    for ev in events:
        if not ev.get("detected"):
            print(f"roofline: guard gate FAILED — poison delivery "
                  f"{ev.get('delivery')} ({ev.get('mode')}) was never "
                  f"detected", file=sys.stderr)
            rc = 1
        elif ev.get("detection_dispatches", 0) > detect_budget:
            print(f"roofline: guard gate FAILED — delivery "
                  f"{ev.get('delivery')} detected after "
                  f"{ev['detection_dispatches']} dispatches (budget "
                  f"{detect_budget})", file=sys.stderr)
            rc = 1
    auc = g.get("auc", {})
    if auc.get("min_served") is None or auc.get("floor") is None or \
            auc["min_served"] < auc["floor"]:
        print(f"roofline: guard gate FAILED — served AUC crossed the "
              f"floor ({auc})", file=sys.stderr)
        rc = 1
    if g.get("batches_quarantined", 0) < 1:
        print("roofline: guard gate FAILED — no batch reached permanent "
              "quarantine despite stream replays", file=sys.stderr)
        rc = 1
    if g.get("rollbacks", 0) < 1:
        print("roofline: guard gate FAILED — no rollback recorded",
              file=sys.stderr)
        rc = 1
    rb = g.get("rollback_ms_last")
    if rb is None or rb > recovery_ms:
        print(f"roofline: guard gate FAILED — rollback+replay took "
              f"{rb} ms (bound {recovery_ms:.0f} ms)", file=sys.stderr)
        rc = 1
    qg = g.get("quality_gate", {})
    if qg.get("rejections", 0) < 1 or \
            qg.get("degraded_reason") != "quality_gate":
        print(f"roofline: guard gate FAILED — the pre-swap canary did "
              f"not reject the poisoned delta visibly ({qg})",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print(
            f"roofline: guard gate ok — {len(events)} poison deliveries "
            f"all detected ≤ {detect_budget} dispatch(es), "
            f"{g.get('rollbacks')} rollback(s) "
            f"(last {rb} ms), {g.get('batches_quarantined')} permanently "
            f"quarantined, min served AUC {auc.get('min_served')} ≥ floor "
            f"{auc.get('floor')}, {g.get('requests')} requests / 0 failed, "
            f"{qg.get('rejections')} canary rejection(s)"
        )
    return rc


def assert_tier(json_path: str, loss_factor: float, step_tol: float) -> int:
    """CI gate for overlapped tier paging (bench.py --tier-paging
    'tier_paging' section; embedding/tier_prefetch.py +
    MultiTierTable.fold_candidates):

      * optimizer-state-loss diet — the fresh-init rate (batch positions
        hitting a tier-resident row, i.e. training from a re-initialized
        row that lost its optimizer state) with paging ON must be at
        least `loss_factor`× lower than the paging-OFF arm on the same
        recorded rotated-zipf stream. An ON rate of exactly 0 passes
        (recorded loss_factor is null — infinite suppression).
      * compile discipline — the fold path recorded 0 steady-state XLA
        compiles (the fixed-chunk sentinel-padded `import_rows`
        discipline applied to folds).
      * stall budget — the training-thread fold stall must not exceed
        the same arm's pinned sync_async boundary stall: paging may not
        cost the training thread more than the maintain machinery it
        relieves.
      * step time — ON step time within `step_tol` of OFF (same
        discipline as --assert-overlap: single-core CI boxes need a
        loose tolerance; accelerator hosts should pin --tier-step-tol
        back down to 0.03).
      * health — zero pump gather errors and a nonzero fold count (a
        bench where nothing folded measured nothing).
    """
    import json

    with open(json_path) as f:
        rec = json.load(f)
    tp = rec.get("tier_paging")
    if not tp:
        print(f"roofline: {json_path} has no 'tier_paging' record "
              "(run bench.py --tier-paging --out onto this JSON)",
              file=sys.stderr)
        return 1
    rc = 0
    off, on = tp.get("off", {}), tp.get("on", {})
    lf = tp.get("loss_factor")
    if lf is not None and lf < loss_factor:
        print(
            f"roofline: tier paging gate FAILED — fresh-init suppression "
            f"{lf}× under the {loss_factor:.0f}× floor (on rate "
            f"{on.get('fresh_init_rate')} vs off "
            f"{off.get('fresh_init_rate')}): folds are not landing before "
            "the lookups", file=sys.stderr,
        )
        rc = 1
    if on.get("steady_compiles") != 0:
        print(
            f"roofline: tier paging gate FAILED — "
            f"{on.get('steady_compiles')} steady-state compile(s) in the "
            "fold path (contract: fixed-chunk folds compile once per "
            "table during warmup, then never)", file=sys.stderr,
        )
        rc = 1
    fold_stall = on.get("fold_stall_ms")
    sync_stall = on.get("sync_stall_ms")
    if fold_stall is None or sync_stall is None or fold_stall > sync_stall:
        print(
            f"roofline: tier paging gate FAILED — training-thread fold "
            f"stall {fold_stall} ms exceeds the arm's sync_async boundary "
            f"stall {sync_stall} ms: paging costs more than the "
            "maintain machinery it relieves", file=sys.stderr,
        )
        rc = 1
    ratio = tp.get("step_time_ratio")
    if ratio is None or ratio > 1.0 + step_tol:
        print(
            f"roofline: tier paging gate FAILED — ON step time "
            f"{ratio}× OFF exceeds the 1+{step_tol:.2f} bound "
            f"(on {on.get('step_ms')} ms vs off {off.get('step_ms')} ms)",
            file=sys.stderr,
        )
        rc = 1
    if on.get("gather_errors", 1) != 0 or not on.get("folded_rows"):
        print(
            f"roofline: tier paging gate FAILED — pump health: "
            f"{on.get('gather_errors')} gather error(s), "
            f"{on.get('folded_rows')} folded row(s) (a run that folded "
            "nothing measured nothing)", file=sys.stderr,
        )
        rc = 1
    if rc == 0:
        print(
            f"roofline: tier paging gate ok — fresh-init suppression "
            f"{'∞' if lf is None else lf}× (floor {loss_factor:.0f}×; "
            f"on {on.get('fresh_init_rate')} vs off "
            f"{off.get('fresh_init_rate')}), {on.get('folded_rows')} rows "
            f"folded ({on.get('fold_bytes')} B), 0 steady compiles, fold "
            f"stall {fold_stall} ms ≤ sync stall {sync_stall} ms, step "
            f"{ratio}× off (bound 1+{step_tol:.2f})"
        )
    return rc


def assert_input(json_path: str, speedup_min: float, train_tol: float) -> int:
    """CI gate for the parallel host input pipeline (tools/bench_input.py
    'input' section; data/pipeline.py + criteo_block_parse):

      * parse throughput — the vectorized block parse must beat the
        serial per-line `criteo_line_parser` by at least `speedup_min`×
        on the same bytes, each at its real operating grain (blocks of
        shard_batches*B records vs B-line calls).
      * parity — the batch stream must be BIT-identical: block parse vs
        line parse on the same records, and the N-worker pipeline vs the
        serial single-reader assembly (any worker count). One mismatched
        element or dtype fails the gate.
      * training thread — host time per dispatch (a pop from the filled
        pipeline buffer) must not exceed `train_tol`× the serial inline
        parse it replaced: the pipeline may not cost the training thread
        more than the work it moved off of it.
    """
    import json

    with open(json_path) as f:
        rec = json.load(f)
    inp = rec.get("input")
    if not inp:
        print(f"roofline: {json_path} has no 'input' record "
              "(run tools/bench_input.py --out onto this JSON)",
              file=sys.stderr)
        return 1
    rc = 0
    speedup = inp.get("block_parse_speedup")
    if speedup is None or speedup < speedup_min:
        parse = inp.get("parse", {})
        print(
            f"roofline: input gate FAILED — block parse "
            f"{speedup}× the serial line parser, under the "
            f"{speedup_min:.1f}× floor ({parse.get('block_exps')} vs "
            f"{parse.get('serial_exps')} ex/s): the vectorized parse "
            "is not paying for the pipeline", file=sys.stderr,
        )
        rc = 1
    if not inp.get("parity_ok"):
        parse_ok = inp.get("parse", {}).get("parse_parity")
        print(
            f"roofline: input gate FAILED — batch-stream parity broken "
            f"(block-vs-line parse parity={parse_ok}; stream parity "
            "covers every benched worker count vs the serial reader): "
            "the pipeline is not bit-identical to the serial path",
            file=sys.stderr,
        )
        rc = 1
    ratio = inp.get("train_thread_ratio")
    if ratio is None or ratio > train_tol:
        tt = inp.get("train_thread", {})
        print(
            f"roofline: input gate FAILED — training-thread dispatch "
            f"cost {ratio}× the serial inline parse exceeds the "
            f"{train_tol:.2f}× bound (pop {tt.get('pop_us')} µs vs "
            f"inline {tt.get('serial_inline_us')} µs): the pipeline "
            "regressed the thread it exists to relieve", file=sys.stderr,
        )
        rc = 1
    if rc == 0:
        tt = inp.get("train_thread", {})
        print(
            f"roofline: input gate ok — block parse {speedup}× serial "
            f"(floor {speedup_min:.1f}×), batch stream bit-identical "
            f"across worker counts, training-thread dispatch "
            f"{tt.get('pop_us')} µs vs {tt.get('serial_inline_us')} µs "
            f"inline ({ratio}× ≤ {train_tol:.2f}×)"
        )
    return rc


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=2048)
    p.add_argument("--emb_dim", type=int, default=16)
    p.add_argument("--capacity", type=int, default=1 << 20)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--peak_bw_gbs", type=float, default=1228.0,
                   help="HBM bandwidth ceiling (GB/s); v4 default")
    p.add_argument("--peak_tflops", type=float, default=275.0,
                   help="bf16 MXU ceiling (TFLOP/s); v4 default")
    p.add_argument("--assert-traffic", metavar="BENCH_JSON", default=None,
                   help="don't run the step: validate the traffic model "
                        "against the op counts recorded in a bench.py JSON "
                        "(CI smoke gate; exits nonzero on drift)")
    p.add_argument("--assert-overlap", metavar="BENCH_JSON", default=None,
                   help="don't run the step: validate the in-step "
                        "pipelining grid recorded in a bench.py JSON "
                        "(pipelined arms present, no regression beyond "
                        "--overlap-tol, overlap efficiency recorded; CI "
                        "smoke gate, exits nonzero on violation)")
    p.add_argument("--overlap-tol", type=float, default=0.5,
                   help="allowed relative K-scan step-time regression of a "
                        "pipelined arm vs 'off' (default 0.5 — generous "
                        "because single-core CI has no overlap to win and "
                        "real noise; TPU runs should pin it down)")
    p.add_argument("--assert-compiles", metavar="BENCH_JSON", default=None,
                   help="don't run the step: validate the steady-state "
                        "compile counts recorded in a bench.py JSON "
                        "(trace_guard section; every timed arm must have "
                        "compiled nothing after its warmup — CI smoke "
                        "gate, exits nonzero on drift)")
    p.add_argument("--compiles-budget", type=int, default=0,
                   help="allowed total steady-state compiles across arms "
                        "(default 0 — the contract is exactly zero)")
    p.add_argument("--assert-hierarchy", metavar="BENCH_JSON", default=None,
                   help="don't run the step: validate the pod-scale 2-D "
                        "mesh arm recorded in a bench.py JSON ('mesh' "
                        "section, --mesh grid): inter-tier modeled bytes "
                        "<= --hierarchy-inter-ratio x flat a2a inter-host "
                        "AND <= flat total/intra, compiled buckets == "
                        "model, 0 overflow, 0 steady compiles, bitwise "
                        "loss parity, nested K-scan within "
                        "--hierarchy-tol; CI smoke gate)")
    p.add_argument("--hierarchy-inter-ratio", type=float, default=0.5,
                   help="required ceiling on modeled hier inter-tier bytes "
                        "as a fraction of the flat a2a's inter-host bytes "
                        "at the reference 2x4 shape (default 0.5)")
    p.add_argument("--hierarchy-tol", type=float, default=0.5,
                   help="allowed relative K-scan step-time regression of "
                        "the nested arm vs the unpipelined hier arm "
                        "(default 0.5 — same rationale as --overlap-tol)")
    p.add_argument("--assert-imbalance", metavar="BENCH_JSON", default=None,
                   help="don't run the step: validate the skew-aware "
                        "placement arm recorded in a bench.py JSON (the "
                        "plan must cut measured per-shard exchange-bytes "
                        "imbalance by --imbalance-factor with step time "
                        "within --imbalance-tol of uniform; CI smoke gate)")
    p.add_argument("--imbalance-factor", type=float, default=2.0,
                   help="required max/mean imbalance reduction of the "
                        "placed plan vs uniform hash (default 2.0)")
    p.add_argument("--imbalance-tol", type=float, default=0.25,
                   help="allowed relative plan-arm step-time regression vs "
                        "the uniform arm (default 0.25 — the skew workload "
                        "is tiny, single-core CI timing is noisy)")
    p.add_argument("--assert-serving", metavar="SERVING_JSON", default=None,
                   help="don't run the step: validate the serving "
                        "scale-out grid recorded by tools/bench_serving.py "
                        "(process scaling floor, quantized residency bytes "
                        "vs the traffic model + zero replay compiles, "
                        "grouped candidates/sec floor; CI smoke gate)")
    p.add_argument("--serving-scale-floor", type=float, default=0.8,
                   help="required per-process speedup fraction at the "
                        "largest process count (default 0.8 — e.g. ≥3.2× "
                        "at 4 processes); gated on the measured arms where "
                        "the host has the cores, on the CPU-split model "
                        "where it doesn't")
    p.add_argument("--serving-grouped-factor", type=float, default=2.0,
                   help="required grouped/ungrouped candidates-per-sec "
                        "factor on the two-tower arm (default 2.0)")
    p.add_argument("--assert-retrieval", metavar="RETRIEVAL_JSON",
                   default=None,
                   help="don't run the step: validate the full-corpus "
                        "retrieval record written by "
                        "tools/bench_retrieval.py (int8 recall@100 "
                        "floor vs exact fp32 scan, blocked-sweep "
                        "speedup over the per-row gather baseline, "
                        "ingest->retrievable freshness vs the pinned "
                        "train_to_serve lag, sweep bytes measured == "
                        "modeled, zero fold compiles; CI smoke gate)")
    p.add_argument("--retrieval-recall-floor", type=float, default=0.95,
                   help="required int8 recall@100 vs exact fp32 scan "
                        "(default 0.95)")
    p.add_argument("--retrieval-sweep-factor", type=float, default=3.0,
                   help="required blocked-sweep speedup over the "
                        "per-row gather baseline (default 3.0)")
    p.add_argument("--retrieval-freshness-factor", type=float,
                   default=2.0,
                   help="bound on ingest->retrievable as a multiple of "
                        "the pinned train_to_serve lag (default 2.0)")
    p.add_argument("--assert-reuse", metavar="SERVING_JSON", default=None,
                   help="don't run the step: validate the frontend "
                        "compute-reuse record written by "
                        "tools/bench_serving.py --compute-reuse "
                        "(cache-on effective qps ≥ --reuse-qps-factor × "
                        "cache-off on the zipf stream, steady hit rate ≥ "
                        "--reuse-hit-floor, miss/hit/no_cache probe "
                        "byte-identical, zero steady compiles, mid-load "
                        "publish dip + recovery with ≥1 invalidation, "
                        "occupancy within the byte budget; CI smoke gate)")
    p.add_argument("--reuse-qps-factor", type=float, default=2.0,
                   help="required cache-on/cache-off effective-qps factor "
                        "on the zipf arm (default 2.0 — the ROADMAP "
                        "headline)")
    p.add_argument("--reuse-hit-floor", type=float, default=0.5,
                   help="required steady-window answer-cache hit rate "
                        "(default 0.5 — the zipf head must be resident)")
    p.add_argument("--assert-fused", metavar="BENCH_JSON", default=None,
                   help="don't run the step: validate the fused-sparse-"
                        "step record written by tools/bench_lookup.py "
                        "--fused-step --out (modeled fused-path HBM "
                        "bytes ≤ --fused-ratio × the split-phase path at "
                        "the recorded shapes, model recomputed here so "
                        "bench and ops/traffic.py can't drift apart, and "
                        "the interpret-mode oracle parity flags all "
                        "true; CI smoke gate)")
    p.add_argument("--fused-ratio", type=float, default=0.6,
                   help="required fused/unfused modeled HBM-byte bound "
                        "(default 0.6 — the no-[U,D]-round-trip, "
                        "no-[N,D]-expansion diet)")
    p.add_argument("--assert-obs", metavar="BENCH_JSON", default=None,
                   help="don't run the step: validate the telemetry-plane "
                        "cost recorded in a bench.py or bench_serving.py "
                        "JSON (instrumented vs DEEPREC_OBS=off arms "
                        "present, modeled overhead under --obs-tol, "
                        "/metrics parse check green; CI smoke gate)")
    p.add_argument("--obs-tol", type=float, default=0.02,
                   help="allowed obs-plane overhead as a fraction of the "
                        "measured step/request time (default 0.02)")
    p.add_argument("--assert-guard", metavar="GUARD_JSON", default=None,
                   help="don't run the step: validate the model-quality "
                        "firewall record written by tools/bench_guard.py "
                        "(every injected poison detected within "
                        "--guard-detect-budget dispatches, served AUC "
                        "never under the recorded floor, zero failed "
                        "requests, permanent quarantine + canary "
                        "rejection observed; CI smoke gate)")
    p.add_argument("--guard-detect-budget", type=int, default=1,
                   help="max dispatches between a poison delivery and its "
                        "sentinel trip (default 1 — the deferred-read "
                        "contract)")
    p.add_argument("--guard-recovery-ms", type=float, default=120000.0,
                   help="bound on the recorded rollback+replay wall time "
                        "(default 120 s — generous for single-core CI; "
                        "capable hosts should pin it down)")
    p.add_argument("--assert-tier", metavar="BENCH_JSON", default=None,
                   help="don't run the step: validate the overlapped "
                        "tier-paging record written by bench.py "
                        "--tier-paging (fresh-init rate with paging on "
                        "≥ --tier-loss-factor× lower than off, 0 "
                        "steady-state fold compiles, fold stall ≤ the "
                        "arm's sync_async stall, step time within "
                        "--tier-step-tol of paging-off; CI smoke gate)")
    p.add_argument("--tier-loss-factor", type=float, default=10.0,
                   help="required fresh-init (optimizer-state-loss) "
                        "suppression factor, paging on vs off "
                        "(default 10)")
    p.add_argument("--tier-step-tol", type=float, default=0.03,
                   help="allowed ON/OFF step-time ratio slack (default "
                        "0.03; CPU CI boxes pass a looser value, same "
                        "precedent as --overlap-tol)")
    p.add_argument("--assert-input", metavar="INPUT_JSON", default=None,
                   help="don't run the step: validate the host input "
                        "pipeline record written by tools/bench_input.py "
                        "(block parse ≥ --input-speedup-min× the serial "
                        "line parser, bit-identical batch stream at every "
                        "benched worker count, training-thread dispatch "
                        "≤ --input-train-tol× the inline parse it "
                        "replaced; CI smoke gate)")
    p.add_argument("--input-speedup-min", type=float, default=2.0,
                   help="required block-parse throughput multiple over "
                        "the serial criteo_line_parser (default 2)")
    p.add_argument("--input-train-tol", type=float, default=1.0,
                   help="allowed training-thread dispatch cost as a "
                        "multiple of the serial inline parse (default 1 "
                        "— the pipeline must never cost the training "
                        "thread more than the work it moved off of it)")
    p.add_argument("--serving-quant-ratio", type=float, default=0.55,
                   help="int8 residency bytes bound as a fraction of fp32 "
                        "(default 0.55 — int8 + per-row scale must at "
                        "least halve the value storage)")
    args = p.parse_args(argv)
    if args.assert_traffic:
        sys.exit(assert_traffic(args.assert_traffic))
    if args.assert_overlap:
        sys.exit(assert_overlap(args.assert_overlap, args.overlap_tol))
    if args.assert_compiles:
        sys.exit(assert_compiles(args.assert_compiles,
                                 args.compiles_budget))
    if args.assert_hierarchy:
        sys.exit(assert_hierarchy(args.assert_hierarchy,
                                  args.hierarchy_inter_ratio,
                                  args.hierarchy_tol))
    if args.assert_imbalance:
        sys.exit(assert_imbalance(args.assert_imbalance,
                                  args.imbalance_factor, args.imbalance_tol))
    if args.assert_serving:
        sys.exit(assert_serving(args.assert_serving,
                                args.serving_scale_floor,
                                args.serving_grouped_factor,
                                args.serving_quant_ratio))
    if args.assert_retrieval:
        sys.exit(assert_retrieval(args.assert_retrieval,
                                  args.retrieval_recall_floor,
                                  args.retrieval_sweep_factor,
                                  args.retrieval_freshness_factor))
    if args.assert_reuse:
        sys.exit(assert_reuse(args.assert_reuse, args.reuse_qps_factor,
                              args.reuse_hit_floor))
    if args.assert_fused:
        sys.exit(assert_fused(args.assert_fused, args.fused_ratio))
    if args.assert_obs:
        sys.exit(assert_obs(args.assert_obs, args.obs_tol))
    if args.assert_guard:
        sys.exit(assert_guard(args.assert_guard, args.guard_detect_budget,
                              args.guard_recovery_ms))
    if args.assert_tier:
        sys.exit(assert_tier(args.assert_tier, args.tier_loss_factor,
                             args.tier_step_tol))
    if args.assert_input:
        sys.exit(assert_input(args.assert_input, args.input_speedup_min,
                              args.input_train_tol))

    import jax
    import jax.numpy as jnp

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import DLRM
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    B, D = args.batch, args.emb_dim
    model = DLRM(emb_dim=D, capacity=args.capacity,
                 bottom=(512, 256, 64, D) if D <= 64 else (512, 256, D))
    trainer = Trainer(model, Adagrad(lr=0.05))
    state = trainer.init(0)
    gen = SyntheticCriteo(batch_size=B, vocab=1_000_000, seed=0)
    batches = [
        {k: jnp.asarray(v) for k, v in gen.batch().items()} for _ in range(8)
    ]
    for i in range(3):
        state, mets = trainer.train_step(state, batches[i % 8])
    jax.block_until_ready(mets["loss"])
    t0 = time.perf_counter()
    for i in range(args.steps):
        state, mets = trainer.train_step(state, batches[i % 8])
    jax.block_until_ready(mets["loss"])
    dt = (time.perf_counter() - t0) / args.steps
    eps = B / dt

    # ---- algorithmic cost accounting (per step) ----
    # Embedding-engine traffic comes from the SHARED model in
    # deeprec_tpu/ops/traffic.py (the one bench.py records and
    # --assert-traffic validates): per unique id, probe key gather + claim
    # scatter, ONE value row gather (the apply reuses the forward
    # residual), one value row scatter, slot row R/W, and one fused [3]
    # int32 metadata gather + scatter.
    from deeprec_tpu.ops.traffic import table_step_traffic

    F = model.num_cat
    vbytes = jnp.dtype(model.features[0].table.value_dtype).itemsize
    U = B  # worst case: all ids unique (synthetic zipf dedups below this)
    per_table = table_step_traffic(
        unique=U, dim=D, value_bytes=vbytes, slot_widths=(D,), diet=True,
    )
    per_table_before = table_step_traffic(
        unique=U, dim=D, value_bytes=vbytes, slot_widths=(D,), diet=False,
    )
    emb_bytes = F * per_table["hbm_bytes"]
    emb_bytes_before = F * per_table_before["hbm_bytes"]
    dense_in = model.num_dense
    fwd = mlp_flops([dense_in] + list(model.bottom), B)
    inter_f = (F + 1) * (F + 1) * D  # dot-interaction matmul per example
    fwd += 2 * inter_f * B
    inter_dim = (F + 1) * F // 2
    fwd += mlp_flops([model.bottom[-1] + inter_dim] + list(model.top), B)
    flops = 3 * fwd  # fwd + ~2x for bwd

    bw_used = emb_bytes / dt / 1e9
    tf_used = flops / dt / 1e12
    frac_bw = bw_used / args.peak_bw_gbs
    frac_tf = tf_used / args.peak_tflops
    roof = "HBM-bandwidth" if frac_bw >= frac_tf else "MXU-compute"
    print(f"backend           : {jax.default_backend()}")
    print(f"examples/sec      : {eps:,.0f}   ({dt * 1e3:.2f} ms/step, batch {B})")
    print(f"embedding traffic : {emb_bytes / 1e6:.1f} MB/step -> {bw_used:,.1f} GB/s "
          f"({frac_bw:.1%} of {args.peak_bw_gbs:.0f} GB/s roof)")
    print(f"   pre-diet model : {emb_bytes_before / 1e6:.1f} MB/step "
          f"({1 - emb_bytes / emb_bytes_before:.1%} removed by "
          f"residual-reuse + fused metadata)")
    print(f"dense compute     : {flops / 1e9:.2f} GFLOP/step -> {tf_used:.2f} TFLOP/s "
          f"({frac_tf:.1%} of {args.peak_tflops:.0f} TFLOP/s roof)")
    print(f"binding roof      : {roof}")
    print(f"headroom          : {1 / max(frac_bw, frac_tf):,.1f}x before the roof "
          f"(upper bound {eps / max(frac_bw, frac_tf):,.0f} ex/s)")


if __name__ == "__main__":
    main()
