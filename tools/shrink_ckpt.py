#!/usr/bin/env python
"""Shrink a checkpoint by dropping filtered (non-admitted) keys.

Parity: the shrink_ckpt_with_filtered_features tool referenced by
docs/docs_en/Embedding-Variable.md — full checkpoints keep sub-threshold
keys so admission counters survive training restarts, but serving-bound
checkpoints don't need them. This rewrites table npz files keeping only rows
with freq >= --min_freq (and optionally versions >= --min_version).

Usage: python tools/shrink_ckpt.py <ckpt_dir>/full-<N> --min_freq 5 [--out DIR]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeprec_tpu.training.checkpoint import is_per_row  # noqa: E402


def shrink_table(path: str, out_path: str, min_freq: int, min_version: int):
    data = dict(np.load(path))
    n = data["keys"].shape[0]
    keep = data["freqs"] >= min_freq
    if min_version > 0:
        keep &= data["versions"] >= min_version
    out = {}
    for k, v in data.items():
        if k == "partition_offset":
            continue  # offsets are invalid after filtering; restore re-probes
        # Route by NAME (checkpoint.is_per_row), never by shape: a bloom
        # sketch or scalar slot whose length happens to equal the row count
        # must pass through untouched.
        out[k] = v[keep] if is_per_row(k) else v
    np.savez(out_path, **out)
    return n, int(keep.sum()), out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("ckpt", help="a full-<step> checkpoint directory")
    p.add_argument("--min_freq", type=int, default=1)
    p.add_argument("--min_version", type=int, default=0)
    p.add_argument("--out", default="", help="output dir (default: <ckpt>-shrunk)")
    args = p.parse_args(argv)

    from deeprec_tpu.training.checkpoint import _array_digest

    out_dir = args.out or args.ckpt.rstrip("/") + "-shrunk"
    os.makedirs(out_dir, exist_ok=True)
    total_before = total_after = 0
    new_digests = {}
    for f in sorted(os.listdir(args.ckpt)):
        src = os.path.join(args.ckpt, f)
        dst = os.path.join(out_dir, f)
        if f.startswith("table_") and f.endswith(".npz"):
            b, a, arrays = shrink_table(src, dst, args.min_freq,
                                        args.min_version)
            new_digests[f] = {k: _array_digest(v) for k, v in arrays.items()}
            total_before += b
            total_after += a
            print(f"{f}: {b} -> {a} rows")
        else:
            shutil.copy(src, dst)
    # Re-stamp the manifest digests for the rewritten table files — the
    # copied originals describe pre-shrink bytes and chain verification
    # would (correctly) quarantine the shrunk dir over them.
    mf_path = os.path.join(out_dir, "manifest.json")
    if os.path.exists(mf_path):
        with open(mf_path) as fh:
            mf = json.load(fh)
        if "digests" in mf:
            mf["digests"].update(new_digests)
            with open(mf_path, "w") as fh:
                json.dump(mf, fh)
    print(f"total: {total_before} -> {total_after} rows "
          f"({out_dir})")


if __name__ == "__main__":
    main()
